"""``serve-bench --generate`` — token-generation benchmark: continuous
batching vs static run-to-completion batching, plus an SLO-goodput
sweep (docs/serving.md "Token generation").

The claim under test is the continuous-batching scheduler itself: with
MIXED output lengths, a run-to-completion batch wastes every slot whose
stream finished early (a batch of 8 decodes until its LONGEST stream is
done), while iteration-level scheduling backfills freed slots from the
queue at every step boundary.  Both arms run the exact same compiled
prefill/decode programs (GraphDecoder) on the same trace, so the ratio
isolates the scheduler:

1. **continuous** — the GenerationEngine, all requests submitted
   back-to-back (max rate): tokens/s plus TTFT (submit -> first token)
   and TPOT (decode-step wall time) percentiles;
2. **static** — groups of ``slots`` requests in arrival order, each
   group prefilled then decoded until EVERY member reached its own
   token budget (finished members idle in their slots — the
   run-to-completion waste being measured);
3. **SLO sweep** (``--slo-sweep``) — offered load at multiples of the
   measured capacity under fifo (unbounded, no deadlines) vs
   shed_oldest (bounded queue + TTFT deadline, PR 8's admission carried
   over): goodput = tokens of requests that completed with TTFT within
   the SLO.

Every row stamps ``device_kind``, ``calibration_digest`` and
``comm_plan_digest`` (PR 7/PR 9 conventions).  Artifact:
``artifacts/serve_generate_r11.json``; the acceptance shape is
continuous >= 2x static tokens/s on the mixed-length trace, and
engine == replicated predict-style decode token-for-token.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

VOCAB = 128


def _build_lm(slots: int, max_seq: int, d_model: int, num_heads: int,
              num_layers: int, seed: int):
    import flexflow_tpu as ff
    from flexflow_tpu.models import build_transformer_lm
    from flexflow_tpu.parallel.mesh import MachineMesh

    cfg = ff.FFConfig(batch_size=4, compute_dtype="float32", seed=seed)
    cfg.serve_gen_slots = slots
    cfg.serve_gen_max_seq = max_seq
    m = build_transformer_lm(
        cfg, num_layers=num_layers, d_model=d_model, num_heads=num_heads,
        d_ff=4 * d_model, seq_len=max_seq, vocab_size=VOCAB)[0]
    m.compile(ff.SGDOptimizer(lr=0.01), mesh=MachineMesh({"n": 1}))
    m.init_layers(seed=seed)
    return m


def make_gen_trace(n: int, prompt_lo: int, prompt_hi: int,
                   short_new: int, long_new: int, long_frac: float,
                   seed: int) -> List[Tuple[np.ndarray, int]]:
    """The mixed-output-length trace: (prompt, max_new_tokens) pairs.
    Bimodal budgets — mostly short answers with a long tail — are the
    regime where run-to-completion batching wastes the most slot-steps
    (every group decodes to its longest member)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        plen = int(rng.integers(prompt_lo, prompt_hi + 1))
        prompt = rng.integers(1, VOCAB, plen).astype(np.int32)
        max_new = long_new if rng.random() < long_frac else short_new
        out.append((prompt, int(max_new)))
    return out


def _pctl(vals: List[float]) -> Dict[str, Optional[float]]:
    from flexflow_tpu.profiling import quantiles
    q = quantiles(vals)

    def ms(v):
        return None if v != v else round(v * 1e3, 3)

    return {"p50_ms": ms(q[0.5]), "p95_ms": ms(q[0.95]),
            "p99_ms": ms(q[0.99])}


def run_continuous(model, trace, slots: int, max_seq: int,
                   stamp: Dict) -> Tuple[Dict, List[List[int]]]:
    """Phase 1: the GenerationEngine at max rate."""
    from .engine import GenerationEngine

    eng = GenerationEngine(model, slots=slots, max_seq=max_seq,
                           stats_every=0)
    useful = sum(mn for _, mn in trace)
    with eng:
        t0 = time.perf_counter()
        streams = [eng.submit(p, max_new_tokens=mn) for p, mn in trace]
        outs = [list(int(t) for t in s.result(timeout=600))
                for s in streams]
        dt = time.perf_counter() - t0
    snap = eng.stats()
    ttfts = [s.ttft for s in streams if s.ttft is not None]
    row = {
        "makespan_s": round(dt, 4),
        "requests": len(trace),
        "tokens": useful,
        "tokens_per_s": round(useful / dt, 2),
        "requests_per_s": round(len(trace) / dt, 2),
        "ttft": _pctl(ttfts),
        "tpot_p50_ms": snap["tpot_p50_ms"],
        "tpot_p95_ms": snap["tpot_p95_ms"],
        "tpot_p99_ms": snap["tpot_p99_ms"],
        **stamp,
    }
    return row, outs


def run_static(model, trace, slots: int, max_seq: int,
               stamp: Dict) -> Tuple[Dict, List[List[int]]]:
    """Phase 2: run-to-completion batching over the SAME compiled
    programs — groups of ``slots`` requests decode until the group's
    longest budget is exhausted; early finishers idle in their slots."""
    import jax

    from .decoder import GraphDecoder

    dec = GraphDecoder.for_model(model, slots, max_seq)
    caches = dec.init_cache()
    outs: List[List[int]] = []
    useful = sum(mn for _, mn in trace)
    steps = 0
    groups = 0
    t0 = time.perf_counter()
    for g0 in range(0, len(trace), slots):
        group = trace[g0:g0 + slots]
        groups += 1
        states = []
        for i, (prompt, max_new) in enumerate(group):
            bucket = dec.prefill_bucket(prompt.size)
            tok = np.zeros((1, bucket), np.int32)
            tok[0, :prompt.size] = prompt
            first, caches = dec.prefill_fn(bucket)(
                model._params, caches, tok, np.int32(i),
                np.int32(prompt.size))
            states.append({
                "last": int(jax.device_get(first)),
                "len": int(prompt.size), "gen": 1, "max": max_new,
                "out": [int(jax.device_get(first))]})
        # run to completion: the WHOLE group steps until its longest
        # member is done — the waste continuous batching removes
        while any(st["gen"] < st["max"] for st in states):
            toks = np.zeros((slots,), np.int32)
            pos = np.zeros((slots,), np.int32)
            for i, st in enumerate(states):
                toks[i] = st["last"]
                pos[i] = min(st["len"], max_seq - 1)
            nxt, caches = dec.decode_fn()(model._params, caches, toks,
                                          pos)
            host = np.asarray(jax.device_get(nxt))
            steps += 1
            for i, st in enumerate(states):
                st["len"] += 1
                if st["gen"] < st["max"]:
                    st["last"] = int(host[i])
                    st["gen"] += 1
                    st["out"].append(int(host[i]))
        outs.extend(st["out"] for st in states)
    dt = time.perf_counter() - t0
    return {
        "makespan_s": round(dt, 4),
        "requests": len(trace),
        "tokens": useful,
        "tokens_per_s": round(useful / dt, 2),
        "groups": groups,
        "decode_steps": steps,
        "slot_steps": steps * slots,
        "slot_efficiency": round(useful / max(1, steps * slots), 4),
        **stamp,
    }, outs


def reference_decode(model, prompt: np.ndarray, max_new: int,
                     max_seq: int) -> List[int]:
    """Replicated predict-style decode: full forward over the padded
    prompt at every step, argmax the last position — the parity
    reference the engine must reproduce token-for-token."""
    toks = [int(t) for t in prompt]
    for _ in range(max_new):
        padded = np.zeros((1, max_seq), np.int32)
        padded[0, :len(toks)] = toks
        probs = model.predict([padded], batch_size=2)
        toks.append(int(np.argmax(probs[0, len(toks) - 1])))
    return toks[len(prompt):]


def run_slo_cell(model, trace, slots: int, max_seq: int, rate: float,
                 policy: str, slo_ms: float, queue_bound: int,
                 seed: int, stamp: Dict) -> Dict:
    """One SLO-sweep cell: Poisson arrivals at ``rate`` req/s; goodput
    counts tokens of requests that completed with TTFT <= slo."""
    from ..bench import make_arrivals
    from ..errors import ServingError
    from .engine import GenerationEngine

    bounded = policy != "fifo"
    eng = GenerationEngine(
        model, slots=slots, max_seq=max_seq, stats_every=0,
        max_queue_requests=queue_bound if bounded else 0,
        admission="shed_oldest" if bounded else "block")
    arrivals = make_arrivals(len(trace), rate, seed, burst=1)
    entries = []
    t0 = time.perf_counter()
    with eng:
        for (prompt, max_new), at in zip(trace, arrivals):
            lag = t0 + at - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            try:
                s = eng.submit(prompt, max_new_tokens=max_new,
                               deadline_ms=slo_ms if bounded else None)
            except ServingError:
                continue  # rejected at admission (counted engine-side)
            entries.append((s, max_new))
        eng.drain(timeout=max(2.0, 16 * slo_ms / 1e3))
    elapsed = max(1e-6, time.perf_counter() - t0)
    snap = eng.stats()
    good_tokens = 0
    completed = 0
    for s, max_new in entries:
        if s.future.done() and s.future.exception() is None \
                and not s.future.cancelled():
            completed += 1
            if s.ttft is not None and s.ttft * 1e3 <= slo_ms:
                good_tokens += len(s.tokens_so_far())
    return {
        "policy": policy,
        "offered_rps": round(rate, 2),
        "offered_requests": len(trace),
        "slo_ms": round(slo_ms, 3),
        "queue_bound": queue_bound if bounded else 0,
        "elapsed_s": round(elapsed, 4),
        "completed": completed,
        "goodput_tokens_per_s": round(good_tokens / elapsed, 2),
        "rejected": snap["rejected"],
        "shed": snap["shed"],
        "expired": snap["expired"],
        "peak_queue_requests": snap["peak_queue_requests"],
        **stamp,
    }


def run_generate_bench(requests: int = 96, slots: int = 8,
                       max_seq: int = 128, prompt_lo: int = 2,
                       prompt_hi: int = 8, short_new: int = 4,
                       long_new: int = 96, long_frac: float = 0.125,
                       d_model: int = 64, num_heads: int = 4,
                       num_layers: int = 2, seed: int = 0,
                       parity_checks: int = 2, slo_sweep: bool = True,
                       slo_ms: float = 0.0,
                       mults=(0.5, 1.0, 2.0),
                       calibration_digest=None) -> Dict:
    """The full --generate payload."""
    import jax

    from ...analysis import comm_plan_digest_for_model
    from ...search.calibration import device_kind as _device_kind

    model = _build_lm(slots, max_seq, d_model, num_heads, num_layers,
                     seed)
    trace = make_gen_trace(requests, prompt_lo, prompt_hi, short_new,
                           long_new, long_frac, seed)
    dk = _device_kind()
    stamp = {"device_kind": dk, "calibration_digest": calibration_digest,
             "comm_plan_digest": comm_plan_digest_for_model(model)}

    # the first engine's start() compiles every bucket + the decode
    # step (engine warmup); the decoder cache shares those programs
    # with every later engine AND the static arm, so both timed phases
    # run fully warm
    cont_row, cont_outs = run_continuous(model, trace, slots, max_seq,
                                         stamp)
    static_row, static_outs = run_static(model, trace, slots, max_seq,
                                         stamp)
    # scheduler isolation check: both arms decode the same tokens
    scheds_agree = all(a == b for a, b in zip(cont_outs, static_outs))

    # engine == replicated predict-style decode, token for token (a
    # greedy stream's first k tokens never depend on later ones, so a
    # bounded prefix check pins the whole trajectory class)
    parity = True
    for i, (prompt, max_new) in enumerate(trace[:parity_checks]):
        want = reference_decode(model, prompt, min(max_new, 8), max_seq)
        if cont_outs[i][:len(want)] != want:
            parity = False
            break

    cells = []
    eff_slo = slo_ms
    if slo_sweep:
        capacity = cont_row["requests_per_s"]
        if eff_slo <= 0:
            p95 = cont_row["ttft"]["p95_ms"] or 50.0
            eff_slo = max(50.0, 4 * p95)
        for mult in mults:
            rate = max(0.5, capacity * mult)
            n = max(8, min(len(trace), int(rate * 2.0)))
            for policy in ("fifo", "shed_oldest"):
                cells.append(run_slo_cell(
                    model, trace[:n], slots, max_seq, rate, policy,
                    eff_slo, 2 * slots, seed + len(cells), stamp)
                    | {"offered_mult": mult})

    payload = {
        "bench": "serve-generate",
        "backend": jax.default_backend(),
        "estimator": "measured",
        **stamp,
        "config": {
            "requests": requests, "slots": slots, "max_seq": max_seq,
            "prompt": f"{prompt_lo}-{prompt_hi}",
            "short_new": short_new, "long_new": long_new,
            "long_frac": long_frac, "d_model": d_model,
            "num_heads": num_heads, "num_layers": num_layers,
            "seed": seed, "vocab": VOCAB,
        },
        "continuous": cont_row,
        "static": static_row,
        "speedup_tokens": round(
            cont_row["tokens_per_s"]
            / max(1e-6, static_row["tokens_per_s"]), 2),
        "parity": {"reference_checks": parity_checks,
                   "engine_eq_reference": bool(parity),
                   "schedulers_agree": bool(scheds_agree)},
        "slo_sweep": {"slo_ms": round(eff_slo, 3), "cells": cells}
        if slo_sweep else None,
    }
    return payload


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="flexflow-tpu serve-bench --generate",
        description="token-generation benchmark: continuous batching "
                    "vs run-to-completion + SLO-goodput sweep "
                    "(docs/serving.md 'Token generation')")
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--prompt", default="2-8",
                    help="prompt-length range, e.g. 2-8")
    ap.add_argument("--short-new", type=int, default=4)
    ap.add_argument("--long-new", type=int, default=96)
    ap.add_argument("--long-frac", type=float, default=0.125,
                    help="fraction of requests with the long token "
                         "budget (the chat-like mostly-short mix)")
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-slo-sweep", action="store_true")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="TTFT SLO for the goodput sweep (0 = auto "
                         "from the measured continuous-phase TTFT)")
    ap.add_argument("--mults", default="0.5,1,2")
    ap.add_argument("--calibration", default="",
                    help="CalibrationTable JSON whose digest the "
                         "payload records")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact here")
    args = ap.parse_args(argv)
    try:
        lo, hi = (int(v) for v in args.prompt.split("-"))
        mults = tuple(float(v) for v in args.mults.split(",")
                      if v.strip())
    except ValueError:
        ap.error(f"bad --prompt {args.prompt!r} or --mults "
                 f"{args.mults!r}")
    if not (1 <= lo <= hi):
        ap.error(f"--prompt wants 1 <= LO <= HI, got {args.prompt!r}")
    digest = None
    if args.calibration:
        from ...search.calibration import CalibrationTable
        try:
            digest = CalibrationTable.load(args.calibration).digest
        except (OSError, ValueError) as e:
            ap.error(f"cannot load --calibration "
                     f"{args.calibration!r}: {e}")

    from ...fflogger import silenced
    with silenced("ff", "serve"):
        payload = run_generate_bench(
            requests=args.requests, slots=args.slots,
            max_seq=args.max_seq, prompt_lo=lo, prompt_hi=hi,
            short_new=args.short_new, long_new=args.long_new,
            long_frac=args.long_frac, d_model=args.d_model,
            num_heads=args.heads, num_layers=args.layers,
            seed=args.seed, slo_sweep=not args.no_slo_sweep,
            slo_ms=args.slo_ms, mults=mults,
            calibration_digest=digest)
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
