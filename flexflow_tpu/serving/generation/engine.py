"""GenerationEngine — iteration-level continuous batching over the
PAGED KV-cached decode path (docs/serving.md "Token generation" +
"Paged KV & prefix caching").

The fixed-shape :class:`~flexflow_tpu.serving.engine.ServingEngine`
coalesces whole requests into one dispatch; token generation is a
different shape of problem — a request is a *stream* whose cost is
unknown up front (EOS may land anywhere).  Run-to-completion batching
wastes every slot whose stream finished early, so this engine schedules
at ITERATION granularity: a fixed ``slots``-wide decode batch shares
one KV **page pool**, requests join a free slot at any step boundary,
every step runs ONE decode dispatch + ONE token fetch for the whole
batch (repo_lint RL010 bans any other host sync in the loop), and a
finished/cancelled stream frees its slot — and its pages — immediately.

Three ISSUE 15 mechanisms ride on the page pool:

* **Paged KV** — per-slot state is a page table of gather indices into
  fixed-size pool pages (``pages.KVPagePool``), so HBM-in-use scales
  with live tokens; ``analysis.kv_memory.kv_page_plan`` is the ONE
  accounting both this engine and lint/explain/the fleet gate read.
* **Shared-prefix reuse** — a ref-counted trie over full pages of
  prompt token ids (``pages.PrefixCache``): a prompt extending a
  cached prefix borrows the shared pages and prefills only its suffix.
  Shared pages are immutable by construction (see pages.py), LRU
  eviction frees unreferenced ones under pool pressure, and
  ``serve_prefix_cache=off`` disables the whole path with bit-identical
  tokens either way — the correctness anchor.
* **Chunked prefill** — long prompts prefill in ``serve_prefill_chunk``
  -token chunks, at most ONE chunk per decode-step boundary
  (Sarathi-style), so a long join stalls in-flight streams by one
  bounded chunk instead of one monolithic prompt.  ``0`` = whole-prompt
  chunks (the pre-paging behavior, program-for-program).

Admission reuses PR 8's machinery unchanged: the same
:class:`~flexflow_tpu.serving.batcher.MicroBatcher` (1 row per request)
provides the bounded queue with block/reject/shed_oldest policies,
per-request deadlines (a prompt still queued past its deadline expires
BEFORE any prefill is burned) and priority classes with the
anti-starvation aging bound — overload semantics carry over verbatim.

Strategy-sharded serving: :meth:`GenerationEngine.from_strategy` loads
a searched ``.pb``, re-places the params under the strategy's
PartitionSpecs (the SNIPPETS partition-rule → spec-pytree pattern) and
shards the pool's head dim over the ``c`` mesh axis
(analysis.kv_memory), so one checkpoint decodes tensor-parallel over
whatever mesh the strategy was searched for.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional

import jax
import numpy as np

from ... import faults
from ...compile_cache import enable as _enable_compile_cache
from ...fflogger import get_logger
from ...obs import lockwatch
from ...obs.flight import flight_dump, get_flight
from ...obs.trace import phase_of, tracer_from_config
from ...profiling import quantiles
from ..batcher import MicroBatcher, Request
from ..errors import (GenerationCancelled, KVCacheExhausted,
                      OverloadError, SheddedError)
from ..metrics import ServingMetrics
from .decoder import GraphDecoder
from .pages import KVPagePool, PrefixCache, export_pages, import_pages
from .sampling import SamplingParams

_END = object()  # token-stream sentinel


def _resolve(fut: Future, out) -> bool:
    """Complete a stream future with a result or exception, from EITHER
    lifecycle state: pending (failure paths fire before the engine
    claimed it at prefill) or running (the decode loop claimed it).
    Unlike the serving engine's ``_resolve_future`` this must NOT call
    ``set_running_or_notify_cancel`` — on an already-claimed (RUNNING)
    future that raises and would silently swallow the resolution.
    Cancelled/finished futures return False (client interference is a
    drop, never a dispatcher-thread exception)."""
    try:
        if isinstance(out, BaseException):
            fut.set_exception(out)
        else:
            fut.set_result(out)
        return True
    except Exception:  # noqa: BLE001 — InvalidStateError & kin
        return False


class GenerationStream:
    """Client handle for one generation request: iterate it for tokens
    as they retire per decode step, or wait on :meth:`result` for the
    full sequence.

    ::

        stream = engine.submit([1, 2, 3], max_new_tokens=16)
        for tok in stream:          # yields as decode steps complete
            ...
        final = stream.result()     # np.int32 array of all new tokens

    ``cancel()`` is safe at any time: a queued request is dropped
    before any prefill; a cancel landing mid-prefill (between chunks,
    or between the prefill dispatch and its scatter) or mid-generation
    frees its KV slot AND pages at the next step boundary and fails
    ONLY this stream with
    :class:`~flexflow_tpu.serving.errors.GenerationCancelled` — tokens
    already iterated remain valid."""

    def __init__(self, prompt_len: int, max_new: int, t_submit: float,
                 deadlined: bool = False, trace: Optional[str] = None,
                 sampling: Optional[SamplingParams] = None,
                 handoff=None):
        self.future: Future = Future()
        # disaggregated prefill/decode (docs/serving.md): when set, the
        # engine offers this stream's KV page chain to the callable at
        # prefill completion (``handoff(payload) -> bool``); True means
        # a DECODE engine adopted the stream and the source frees its
        # slot, False/raise falls back to co-located decode failing
        # nothing.  Set at submit() — the router's migration hook.
        self.handoff = handoff
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self.t_submit = t_submit
        self.deadlined = deadlined
        # per-request sampling strategy (None/greedy keeps the stream
        # on the unsampled argmax programs — the bit-parity anchor)
        self.sampling = sampling
        # sampled trace id (obs.trace) or None; the engine records this
        # stream's queue/prefill/terminal spans against it
        self.trace = trace
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._tokens: List[int] = []  # engine-thread writes, then frozen
        self._cancelled = threading.Event()
        # submit -> first token, set by the engine at the final prefill
        # chunk (None until then) — per-stream SLO evidence for the
        # goodput sweep
        self.ttft: Optional[float] = None

    # ---- client side ---------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation.  Queued: the engine drops the request
        without a prefill (the future flips cancelled).  Prefilling or
        generating: the slot and its pages free at the next step
        boundary and the future fails with GenerationCancelled."""
        self._cancelled.set()
        # succeeds only while still queued (the engine claims the
        # future before prefill); a claimed future fails at the next
        # step boundary instead
        if self.future.cancel():
            self._q.put(_END)

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def tokens_so_far(self) -> List[int]:
        """Snapshot of the tokens retired so far (grows per step)."""
        return list(self._tokens)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full generated sequence (np.int32, length <= max_new) —
        blocks until EOS/max-tokens; raises the stream's failure."""
        return self.future.result(timeout)

    # ---- engine side ---------------------------------------------------
    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._q.put(tok)

    def _finish(self) -> bool:
        done = _resolve(self.future, np.asarray(self._tokens, np.int32))
        self._q.put(_END)
        return done

    def _fail(self, exc: BaseException) -> bool:
        done = _resolve(self.future, exc)
        if done:
            self._q.put(exc)
        self._q.put(_END)
        return done


class _GenRequest(Request):
    """A queued prompt: a 1-row batcher Request carrying its stream.

    Deliberately NO ``stale=`` predicate: a cancelled-while-queued
    stream is already dropped at join time (the engine's
    ``set_running_or_notify_cancel`` claim fails on a cancelled
    future, so no prefill is burned), and a stale hook on EVERY
    request would flip the batcher's ``_watch`` fast path permanently
    on — every ``reap_expired()``/``poll()`` the decode loop runs
    would scan the whole queue under the lock even when nothing
    carries a deadline."""

    __slots__ = ("stream",)

    def __init__(self, stream: GenerationStream, prompt: np.ndarray,
                 on_done, t_submit: float, deadline=None, priority=0):
        super().__init__((prompt,), 1, on_done, t_submit,
                         deadline=deadline, priority=priority)
        self.stream = stream


class _Slot:
    """Dispatcher-thread-only state of one decode slot: its stream,
    its page list (prefix-cache hits first, private pages after), and
    its prefill progress.  ``prefilling`` slots own pages but are
    excluded from decode dispatch writes (their write page rides the
    pool's OOB sentinel)."""

    __slots__ = ("stream", "prompt", "pages", "draft_pages",
                 "hit_tokens", "next_pos", "chunks", "last_token",
                 "length", "generated", "prefilling", "t_join")

    def __init__(self, stream: GenerationStream, prompt: np.ndarray,
                 hit_pages: List[int], page_size: int, t_join: float):
        self.stream = stream
        self.prompt = prompt
        self.pages: List[int] = list(hit_pages)
        # the slot's pages in the DRAFT pool under speculation (no
        # prefix sharing: draft rows are never promoted to the trie)
        self.draft_pages: List[int] = []
        self.hit_tokens = len(hit_pages) * int(page_size)
        self.next_pos = self.hit_tokens  # next prompt position to prefill
        self.chunks = 0
        self.last_token = 0
        self.length = 0     # positions materialized in the cache
        self.generated = 0
        self.prefilling = True
        self.t_join = t_join


class GenerationMetrics(ServingMetrics):
    """ServingMetrics plus the generation gauges: windowed tokens/s,
    TTFT (submit -> first token, i.e. queue wait + prefill) and TPOT
    (decode-step wall time — the per-token latency every active stream
    pays) percentiles, token/prefill totals, and — when the engine
    wires ``pool_stats_fn`` — the page-pool view (kv_pages_in_use,
    prefix_hit_rate, evictions, prefill_chunks).  Emitted as
    ``gen_stats`` events, the generation analogue of ``serve_stats``."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._ttfts: deque = deque(maxlen=4096)  # guarded_by: self._lock
        self._steps: deque = deque()             # guarded_by: self._lock
        # the engine's page-pool/prefix-cache snapshot provider (plain
        # attribute like queue_depth_fn; released with it)
        self.pool_stats_fn = None
        # token/prefill lifetime totals live in the obs.registry like
        # every other serving counter — gen_stats events and /metrics
        # read the same children (docs/observability.md "Metrics")
        from ...obs.registry import get_registry
        reg = get_registry()
        kv = {"model": self.model_tag, "eng": self.eng_id}
        # into self._fams too: unregister() must reclaim these series
        # with the rest (the fleet's bounded-retirement scheme)
        self._fams["tokens"] = reg.counter(
            "ff_gen_tokens_total", "Tokens generated (incl. the "
            "prefill's first token)", ("model", "eng"))
        self._fams["prefills"] = reg.counter(
            "ff_gen_prefills_total", "Prefill completions (stream "
            "joins)", ("model", "eng"))
        self._ctr["tokens"] = self._fams["tokens"].labels(**kv)
        self._ctr["prefills"] = self._fams["prefills"].labels(**kv)
        # speculative-decoding counters (ISSUE 16): registry-backed so
        # gen_stats events and the /metrics scrape read the SAME
        # children and can never diverge.  accept_rate in snapshot()
        # is derived from these two totals, not tracked separately.
        self._fams["draft_dispatches"] = reg.counter(
            "ff_gen_draft_dispatches_total", "Speculative draft "
            "dispatches (one γ-step scan per round)", ("model", "eng"))
        self._fams["spec_proposed"] = reg.counter(
            "ff_gen_spec_proposed_tokens_total", "Draft tokens "
            "proposed to the verifier", ("model", "eng"))
        self._fams["spec_accepted"] = reg.counter(
            "ff_gen_spec_accepted_tokens_total", "Draft tokens the "
            "verifier accepted", ("model", "eng"))
        self._fams["spec_fallbacks"] = reg.counter(
            "ff_gen_spec_fallbacks_total", "Demotions to plain decode "
            "(draft failure or accept-rate collapse)", ("model", "eng"))
        for k in ("draft_dispatches", "spec_proposed", "spec_accepted",
                  "spec_fallbacks"):
            self._ctr[k] = self._fams[k].labels(**kv)
        # the engine's live speculation view (current γ, policy, state)
        # merged into snapshot() like pool_stats_fn
        self.spec_stats_fn = None

    @property
    def total_tokens(self) -> int:
        return int(self._ctr["tokens"].value)

    @property
    def total_prefills(self) -> int:
        return int(self._ctr["prefills"].value)

    def record_ttft(self, seconds: float) -> None:
        now = self.clock()
        self._ctr["prefills"].inc()
        with self._lock:
            self._ttfts.append((now, float(seconds)))

    def record_decode_step(self, ntokens: int, step_s: float) -> None:
        now = self.clock()
        self._ctr["tokens"].inc(int(ntokens))
        with self._lock:
            self._steps.append((now, int(ntokens), float(step_s)))
            horizon = now - self.window_s
            while self._steps and self._steps[0][0] < horizon:
                self._steps.popleft()

    def record_spec_round(self, proposed: int, accepted: int) -> None:
        """One speculative round: one draft dispatch, ``proposed``
        draft tokens judged, ``accepted`` of them kept."""
        self._ctr["draft_dispatches"].inc()
        self._ctr["spec_proposed"].inc(int(proposed))
        self._ctr["spec_accepted"].inc(int(accepted))

    def record_spec_fallback(self) -> None:
        self._ctr["spec_fallbacks"].inc()

    def record_prefill_token(self) -> None:
        """The prefill's first token counts toward tokens/s too."""
        now = self.clock()
        self._ctr["tokens"].inc()
        with self._lock:
            self._steps.append((now, 1, 0.0))
            # trim here too: a max_new_tokens=1 workload never calls
            # record_decode_step, and the window must stay bounded
            horizon = now - self.window_s
            while self._steps and self._steps[0][0] < horizon:
                self._steps.popleft()

    def release(self) -> None:
        # drop the engine-owned pool provider with the queue-depth one
        # (a retired engine must not be retained by the registry)
        self.pool_stats_fn = None
        self.spec_stats_fn = None
        super().release()

    def snapshot(self) -> Dict:
        snap = super().snapshot()
        now = self.clock()
        with self._lock:
            steps = list(self._steps)
            ttfts = [v for _, v in self._ttfts]
            total_tokens = self.total_tokens
            total_prefills = self.total_prefills
        span = self.window_s
        if steps:
            span = min(self.window_s, max(1e-6, now - steps[0][0]))
        toks = sum(s[1] for s in steps)
        tpots = [s[2] for s in steps if s[2] > 0]
        qt = quantiles(ttfts)
        qp = quantiles(tpots)

        def ms(v):
            return None if v != v else round(v * 1e3, 3)

        proposed = int(self._ctr["spec_proposed"].value)
        accepted = int(self._ctr["spec_accepted"].value)
        snap.update({
            "tokens_per_s": round(toks / span, 3),
            "tokens": total_tokens,
            "prefills": total_prefills,
            "ttft_p50_ms": ms(qt[0.5]), "ttft_p95_ms": ms(qt[0.95]),
            "ttft_p99_ms": ms(qt[0.99]),
            "tpot_p50_ms": ms(qp[0.5]), "tpot_p95_ms": ms(qp[0.95]),
            "tpot_p99_ms": ms(qp[0.99]),
            # speculation totals (under speculation a "step" is a
            # draft+verify ROUND, so tpot_* percentiles are per-round
            # walls — tokens_per_s stays the honest cross-mode metric)
            "draft_dispatches": int(
                self._ctr["draft_dispatches"].value),
            "spec_proposed_tokens": proposed,
            "spec_accepted_tokens": accepted,
            "accept_rate": (round(accepted / proposed, 4)
                            if proposed else 0.0),
            "spec_fallbacks": int(self._ctr["spec_fallbacks"].value),
        })
        for fn in (self.pool_stats_fn, self.spec_stats_fn):
            if fn is not None:
                snap.update(fn())
        return snap

    def emit(self, extra: Dict | None = None) -> None:
        # eng rides as an event field for the same reason as
        # serve_stats': the cluster router's scrape keys on it
        get_logger("serve").event("gen_stats", eng=self.eng_id,
                                  **self.snapshot(), **(extra or {}))


class GenerationEngine:
    """Continuous-batching token generation over a compiled+initialized
    FFModel LM graph.

    ::

        engine = GenerationEngine(model, slots=8, eos_id=0)
        with engine:
            stream = engine.submit(prompt_ids, max_new_tokens=32)
            for tok in stream: ...
            out = stream.result()

    Knobs resolve from ``model.config`` (``--serve-gen-slots``,
    ``--serve-gen-max-seq``, ``--serve-gen-max-new``, the paged-KV
    knobs ``--serve-kv-page``/``--serve-kv-pages``/
    ``--serve-prefix-cache``/``--serve-prefill-chunk``, and PR 8's
    ``--serve-max-queue-rows``/``--serve-admission``/
    ``--serve-starvation-ms`` for admission — the queue bound counts
    REQUESTS here, one row each) unless overridden.  ``clock``/``sleep``
    are injectable for deterministic fault tests (RL008)."""

    # speculation guardrails (class attrs so tests can tighten them):
    # a draft whose EWMA accept rate sits below _SPEC_COLLAPSE_ACCEPT
    # after _SPEC_COLLAPSE_MIN_PROPOSED proposals costs more than it
    # saves — demote to plain decode rather than burn a draft dispatch
    # per round for nothing
    _SPEC_COLLAPSE_MIN_PROPOSED = 64
    _SPEC_COLLAPSE_ACCEPT = 0.1
    _SPEC_EWMA_ALPHA = 0.2        # per-round accept/cost EWMA weight
    _SPEC_RETUNE_EVERY = 16       # adaptive γ re-pricing cadence

    def __init__(self, model, slots: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue_requests: Optional[int] = None,
                 admission: Optional[str] = None,
                 starvation_ms: Optional[float] = None,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_cache: Optional[str] = None,
                 draft_model=None,
                 spec_gamma: Optional[int] = None,
                 spec_gamma_max: Optional[int] = None,
                 spec_policy: Optional[str] = None,
                 stats_every: int = 32, metrics_window_s: float = 30.0,
                 clock=time.monotonic, sleep=time.sleep,
                 name: str = "", device=None):
        assert model._compiled, "compile() + init_layers() the model first"
        _enable_compile_cache()
        cfg = model.config
        if getattr(cfg, "serve_quantize", "") or \
                getattr(model, "_quantized", ""):
            # weight quantization is a DENSE-serving feature (the fleet
            # schema rejects it on generation tenants for the same
            # reason): silently serving full-precision weights while
            # the operator budgets HBM for int8 would overcommit the
            # KV+weight capacity plan
            raise ValueError(
                "serve_quantize is not supported by the generation "
                "engine (weight quantization covers dense serving "
                "only); unset FFConfig.serve_quantize for this model")
        self.model = model
        # ``device`` pins THIS engine's dispatches to one jax device:
        # its params copy is committed there, and every program
        # (prefill/decode/verify) follows the committed operand, so
        # N co-resident engines drive N accelerators independently —
        # the disaggregated cluster's placement primitive (a second
        # host-platform CPU device stands in for the second chip in
        # single-host runs).  None = the model's own placement.
        self.device = device
        if device is None:
            self._params = model._params
        else:
            import jax
            self._params = jax.device_put(model._params, device)
        self.slots = int(slots or cfg.serve_gen_slots)
        seq_len = (model.input_tensors[0].shape[1]
                   if model.input_tensors else 0)
        self.max_seq = int(max_seq or cfg.serve_gen_max_seq or seq_len)
        self.max_new_tokens = int(max_new_tokens
                                  or cfg.serve_gen_max_new_tokens)
        self.eos_id = eos_id
        self.clock = clock
        self._sleep = sleep
        self.stats_every = int(stats_every)
        self.admission = (cfg.serve_admission if admission is None
                          else admission)
        self.max_queue_requests = int(
            cfg.serve_max_queue_rows if max_queue_requests is None
            else max_queue_requests)
        self._batcher = MicroBatcher(
            1, 0.0, clock=clock, max_queue_rows=self.max_queue_requests,
            admission=self.admission,
            starvation_ms=float(cfg.serve_starvation_ms
                                if starvation_ms is None
                                else starvation_ms))
        # tenant identity, stamped on gen_stats/gen_* events (fleet
        # co-residency: N engines in one process stay distinguishable;
        # FFConfig.serve_model_name is the single-engine default)
        self.name = str(name or cfg.serve_model_name)
        self.metrics = GenerationMetrics(
            window_s=metrics_window_s, clock=clock,
            queue_depth_fn=lambda: self._batcher.queue_depth,
            model=self.name)
        # observability plane: same contract as ServingEngine — one
        # lock-free `active` read per decode step when tracing is off,
        # flight taps installed for post-mortem dumps
        self._tracer = tracer_from_config(cfg)
        get_flight()
        self._decoder = GraphDecoder.for_model(
            model, self.slots, self.max_seq,
            page_size=int(page_size or 0), num_pages=int(num_pages or 0))
        self.page_size = self._decoder.page_size
        self.num_pages = self._decoder.num_pages
        # the ONE KV accounting (analysis.kv_memory): what lint's
        # FF108/FF121 gates and the fleet's FF130 gate charge for this
        # deployment is what the pool actually allocates
        from ...analysis.kv_memory import dtype_bytes, kv_page_plan
        self.kv_plan = kv_page_plan(
            model.layers,
            dict(model.mesh.sizes) if model.mesh is not None else None,
            self.slots, self.max_seq,
            kv_dtype_bytes=dtype_bytes(cfg.compute_dtype),
            page_size=self.page_size, num_pages=self.num_pages)
        self.kv_cache_bytes = self.kv_plan["total_bytes"]
        # chunked prefill: at most one chunk per step boundary; 0 =
        # whole-prompt chunks (the monolithic baseline).  LSTM graphs
        # cannot chunk (cell state is not a program input mid-prompt).
        chunk = int(cfg.serve_prefill_chunk if prefill_chunk is None
                    else prefill_chunk)
        if chunk < 0:
            raise ValueError(f"serve_prefill_chunk must be >= 0, "
                             f"got {chunk}")
        self.prefill_chunk = (chunk if self._decoder.supports_chunking
                              else 0)
        # shared-prefix cache: on unless configured off; needs the
        # paged attention path (and whole-prompt LSTM graphs have no
        # pageable state to share)
        pc = (cfg.serve_prefix_cache if prefix_cache is None
              else prefix_cache)
        self.prefix_cache_enabled = (
            str(pc).lower() not in ("off", "0", "false", "no")
            and self._decoder.has_attention
            and self._decoder.supports_chunking)
        # dispatcher-thread-only state (single writer, no lock)
        self._slots_state: List[Optional[_Slot]] = [None] * self.slots
        self._pool = KVPagePool(self.num_pages, self.page_size)
        self._prefix: Optional[PrefixCache] = (
            PrefixCache(self._pool) if self.prefix_cache_enabled
            else None)
        self._table = np.full((self.slots, self._decoder.pages_per_slot),
                              self._pool.no_page, np.int32)
        self._prefill_q: deque = deque()  # (slot, _Slot) FIFO
        # migrated-stream inbox (disaggregated serving): the ROUTER's
        # handoff appends host-only payloads from the SOURCE engine's
        # dispatcher thread; this thread drains it at step boundaries
        # (CPython deque append/popleft are atomic — no lock, no
        # cross-engine lock-order edge for the fflock gate to flag)
        self._adopt_q: deque = deque()
        # per-migration wall costs (ms), export side and import side —
        # the calibrated-replay bench reads these as the REAL price of
        # a migration on this substrate
        self.migrate_export_ms: List[float] = []
        self.migrate_import_ms: List[float] = []
        self._caches = None
        self._n_steps = 0
        self._chunks_total = 0
        self._hit_tokens = 0
        self._prompt_tokens = 0
        # lifetime counters preserved across pool rebuilds (a poisoned
        # dispatch rebuilds pool+prefix; totals must stay monotonic)
        self._evictions_base = 0
        self._pool_high_base = 0
        self.metrics.pool_stats_fn = self._pool_stats
        # ---- speculative decoding (docs/serving.md "Speculative
        # decoding & sampling"): a co-hosted DRAFT model proposes γ
        # tokens per round in one scanned dispatch; the target verifies
        # the whole window in one chunked-prefill-class dispatch.  The
        # draft owns its OWN page pool/table/caches with the SAME
        # geometry (its rows mirror the target's positions 1:1), and
        # the fleet gate charges them byte-for-byte.
        self.draft_model = draft_model
        self._draft_params = None
        self._draft_decoder = None
        self._draft_pool: Optional[KVPagePool] = None
        self._draft_table = None
        self._draft_caches = None
        self.draft_kv_cache_bytes = 0
        g = int(cfg.serve_spec_gamma if spec_gamma is None
                else spec_gamma) if draft_model is not None else 0
        gmax = int(getattr(cfg, "serve_spec_gamma_max", 4)
                   if spec_gamma_max is None else spec_gamma_max)
        pol = str(getattr(cfg, "serve_spec_policy", "fixed")
                  if spec_policy is None else spec_policy)
        if pol not in ("fixed", "adaptive"):
            raise ValueError(f"spec_policy must be 'fixed' or "
                             f"'adaptive', got {pol!r}")
        if draft_model is not None:
            assert draft_model._compiled, \
                "compile() + init_layers() the draft model first"
            if pol == "fixed" and g == 0:
                raise ValueError(
                    "draft_model given but speculation is off "
                    "(serve_spec_gamma=0, policy 'fixed'): set "
                    "--serve-spec-gamma >= 2 or policy 'adaptive'")
            if g != 0 and g < 2:
                raise ValueError(
                    f"spec_gamma must be 0 (off) or >= 2, got {g}: a "
                    f"1-row verify window lowers matrix-vector kernels "
                    f"whose bits drift from the full forward (same "
                    f"floor as slots/serve_buckets)")
            if gmax < max(g, 2):
                raise ValueError(f"spec_gamma_max {gmax} < gamma "
                                 f"{max(g, 2)}")
            if not (self._decoder.has_attention
                    and self._decoder.supports_chunking):
                raise ValueError(
                    "speculative decoding needs a chunkable causal-"
                    "attention graph (LSTM state cannot roll back to "
                    "an accept point)")
            self._draft_decoder = GraphDecoder.for_model(
                draft_model, self.slots, self.max_seq,
                page_size=self.page_size, num_pages=self.num_pages)
            if not self._draft_decoder.supports_chunking:
                raise ValueError("draft model must be a chunkable "
                                 "attention graph too")
            tv = self._decoder.model.layers[-1].outputs[0].shape[-1]
            dv = draft_model.layers[-1].outputs[0].shape[-1]
            if tv != dv:
                raise ValueError(f"draft vocab {dv} != target vocab "
                                 f"{tv}: the proposals would not be "
                                 f"token ids of the target")
            self.draft_kv_plan = kv_page_plan(
                draft_model.layers,
                dict(draft_model.mesh.sizes)
                if draft_model.mesh is not None else None,
                self.slots, self.max_seq,
                kv_dtype_bytes=dtype_bytes(cfg.compute_dtype),
                page_size=self.page_size, num_pages=self.num_pages)
            self.draft_kv_cache_bytes = self.draft_kv_plan["total_bytes"]
            if device is None:
                self._draft_params = draft_model._params
            else:
                import jax
                self._draft_params = jax.device_put(
                    draft_model._params, device)
            self._draft_pool = KVPagePool(self.num_pages, self.page_size)
            self._draft_table = np.full(
                (self.slots, self._draft_decoder.pages_per_slot),
                self._draft_pool.no_page, np.int32)
        self.spec_policy = pol
        self.spec_gamma_max = gmax
        # candidate γs the adaptive controller prices (fixed: just γ)
        if draft_model is None:
            self._spec_candidates: List[int] = []
        elif pol == "fixed":
            self._spec_candidates = [g]
        else:
            self._spec_candidates = sorted(
                {c for c in (2, 4, gmax) if 2 <= c <= gmax})
        self._spec_gamma = (self._spec_candidates[0]
                            if self._spec_candidates else 0)
        if pol == "fixed" and g:
            self._spec_gamma = g
        self._spec_on = draft_model is not None
        self._spec_rounds = 0
        self._accept_ewma: Optional[float] = None
        self._spec_seen_proposed = 0
        self._spec_costs: Dict[int, float] = {}  # per-γ round-wall EWMA
        self.metrics.spec_stats_fn = self._spec_stats
        self._gen_faults: List[Dict] = []
        # lifecycle (same single-use contract as ServingEngine)
        self._thread: Optional[  # guarded_by: self._lifecycle
            threading.Thread] = None
        self._stopped = False    # guarded_by: self._lifecycle
        self._draining = False   # guarded_by: self._lifecycle
        self._finalized = False  # guarded_by: self._lifecycle
        self._lifecycle = lockwatch.lock("GenerationEngine._lifecycle")
        self._closing = threading.Event()
        self._abort = threading.Event()
        self._shutdown_done = threading.Event()

    # ---- lifecycle -----------------------------------------------------
    def _warmup(self) -> None:
        """Compile every program the engine can dispatch BEFORE
        serving — the generation edition of ServingEngine's bucket
        warmup.  A chunk bucket compiled lazily mid-serving stalls
        the whole decode batch for the compile (measured ~0.6 s/bucket
        on CPU — every in-flight stream's TPOT eats it); paying all of
        it at start() keeps steady-state latency flat.  The dummy
        dispatches ride an all-sentinel page table, so every pool
        write DROPS — warmup leaves the pool bit-clean."""
        params = self._params
        no_table = np.full((self._decoder.pages_per_slot,),
                           self._pool.no_page, np.int32)
        for b in self._decoder.buckets:
            fn = self._decoder.prefill_fn(b)
            tokens = np.zeros((1, b), np.int32)
            _, self._caches = fn(params, self._caches, tokens, no_table,
                                 np.int32(0), np.int32(0), np.int32(1))
        nxt, self._caches = self._decoder.decode_fn()(
            params, self._caches, np.zeros((self.slots,), np.int32),
            np.zeros((self.slots,), np.int32),
            np.full((self.slots, self._decoder.pages_per_slot),
                    self._pool.no_page, np.int32),
            np.full((self.slots,), self._pool.no_page, np.int32),
            np.zeros((self.slots,), np.int32))
        jax.device_get(nxt)
        if self._spec_on:
            self._warmup_spec()

    def _warmup_spec(self) -> None:
        """Compile the draft prefill buckets plus the draft-scan and
        verify programs for every candidate γ (greedy variants; the
        sampled ones compile on the first sampled request), and TIME
        one dummy round per γ — the calibrated per-dispatch cost the
        adaptive controller prices against the live accept rate.
        Sentinel tables again: warmup writes all drop."""
        dparams = self._draft_params
        ddec = self._draft_decoder
        no_row = np.full((ddec.pages_per_slot,),
                         self._draft_pool.no_page, np.int32)
        for b in ddec.buckets:
            fn = ddec.prefill_fn(b)
            _, self._draft_caches = fn(
                dparams, self._draft_caches, np.zeros((1, b), np.int32),
                no_row, np.int32(0), np.int32(0), np.int32(1))
        dtable = np.full((self.slots, ddec.pages_per_slot),
                         self._draft_pool.no_page, np.int32)
        vtable = np.full((self.slots, self._decoder.pages_per_slot),
                         self._pool.no_page, np.int32)
        tokens = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        for g in self._spec_candidates:
            dwp = np.full((g, self.slots), self._draft_pool.no_page,
                          np.int32)
            dwr = np.zeros((g, self.slots), np.int32)
            vwp = np.full((self.slots, g), self._pool.no_page, np.int32)
            vwr = np.zeros((self.slots, g), np.int32)
            dfn = ddec.draft_fn(g)
            vfn = self._decoder.verify_fn(g)
            # compile pass, then one timed pass = the per-γ cost seed
            for probe in range(2):
                t0 = self.clock()
                d, self._draft_caches = dfn(
                    dparams, self._draft_caches, tokens, pos, dtable,
                    dwp, dwr)
                (n_acc, out), self._caches = vfn(
                    self._params, self._caches, tokens, d, pos,
                    vtable, vwp, vwr)
                jax.device_get((n_acc, out))
                if probe:
                    self._spec_costs[g] = max(1e-6,
                                              self.clock() - t0)

    def start(self, warmup: bool = True) -> "GenerationEngine":
        with self._lifecycle:
            if self._stopped:
                raise RuntimeError(
                    "engine was stopped; create a new GenerationEngine "
                    "(decoders cache their compiled programs on the "
                    "model, so a fresh engine starts warm)")
            if self._thread is None:
                self._caches = self._decoder.init_cache()
                if self._spec_on:
                    self._draft_caches = self._draft_decoder.init_cache()
                if warmup:
                    self._warmup()
                self._gen_faults = _load_gen_faults()
                get_logger("serve").event(
                    "gen_engine_start", model=self.name, slots=self.slots,
                    max_seq=self.max_seq,
                    kv_cache_bytes=self.kv_cache_bytes,
                    kv_page_size=self.page_size,
                    kv_num_pages=self.num_pages,
                    prefix_cache=("on" if self.prefix_cache_enabled
                                  else "off"),
                    prefill_chunk=self.prefill_chunk,
                    admission=self.admission,
                    max_queue_requests=self.max_queue_requests,
                    **self._spec_stats())
                self._thread = threading.Thread(
                    target=self._decode_loop, name="ff-generate",
                    daemon=True)
                self._thread.start()
        return self

    def stop(self) -> None:
        """Close admissions, serve everything queued and in flight to
        completion, stop the dispatcher, emit final stats.  Idempotent;
        single-use (see start()).  For a BOUNDED shutdown that sheds
        stragglers, see :meth:`drain`."""
        to_fail: List[Request] = []
        err = now = None
        with self._lifecycle:
            self._closing.set()
            self._batcher.close()
            if self._thread is not None:
                # lock-ok: dispatcher never takes _lifecycle, so joining
                # it under the lock cannot deadlock
                self._thread.join()
                self._thread = None
                if not self._finalized:
                    self._finalized = True
                    self.metrics.emit(extra={"final": True,
                                             "slots": self.slots})
            else:
                now = self.clock()
                err = SheddedError(
                    "engine stopped before it was started")
                to_fail = self._batcher.fail_pending()
            self._stopped = True
        # resolve OUTSIDE _lifecycle: on_done's future callbacks take
        # locks the static graph cannot see through a stored callable
        for r in to_fail:
            r.on_done(err, now)
        # same registry retirement as ServingEngine.stop()
        self.metrics.release()
        self._shutdown_done.set()

    def drain(self, timeout: Optional[float] = None) -> Dict:
        """Bounded graceful shutdown: stop admitting, give in-flight
        generation ``timeout`` seconds, then shed the stragglers
        (queued prompts AND active streams fail with SheddedError).
        Returns the final stats snapshot; the engine is stopped
        afterwards."""
        with self._lifecycle:
            already = self._stopped or self._draining
            thread = self._thread
            if not already:
                self._draining = True
                self._closing.set()
                self._batcher.close()
        if already:
            self._shutdown_done.wait()
            return self.stats()
        get_logger("serve").event(
            "gen_drain", model=self.name, timeout_s=timeout,
            queue_depth=self._batcher.queue_depth)
        shed = 0
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                self._abort.set()
                now = self.clock()
                for r in self._batcher.fail_pending():
                    if r.on_done(SheddedError(
                            f"engine drained with work still queued "
                            f"(drain timeout {timeout}s)"), now):
                        shed += 1
                thread.join(timeout)
        else:
            now = self.clock()
            for r in self._batcher.fail_pending():
                if r.on_done(SheddedError(
                        "engine drained before it was started"), now):
                    shed += 1
        with self._lifecycle:
            self._stopped = True
            self._draining = False
            self._thread = None
            first = not self._finalized
            self._finalized = True
        snap = self.stats()
        if first:
            self.metrics.emit(extra={"final": True, "slots": self.slots,
                                     "drain_shed": shed})
        self.metrics.release()
        self._shutdown_done.set()
        return snap

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- fleet-managed (external) dispatch -----------------------------
    def begin_external_dispatch(self, warmup: bool = True
                                ) -> "GenerationEngine":
        """Fleet mode: ready the engine WITHOUT its own decode thread —
        a :class:`~flexflow_tpu.serving.fleet.FleetEngine` drives
        :meth:`dispatch_pending` decode steps from ONE shared
        dispatcher, interleaved with its co-resident tenants' dense
        dispatches under weighted-fair scheduling.  The producer side
        (submit, admission, deadlines) behaves exactly as under
        :meth:`start`."""
        with self._lifecycle:
            if self._stopped:
                raise RuntimeError(
                    "engine was stopped; create a new GenerationEngine")
            if self._thread is not None:
                raise RuntimeError(
                    "engine already runs its own decode thread")
            if self._caches is None:
                self._caches = self._decoder.init_cache()
                if self._spec_on:
                    self._draft_caches = self._draft_decoder.init_cache()
                if warmup:
                    self._warmup()
                self._gen_faults = _load_gen_faults()
                get_logger("serve").event(
                    "gen_engine_start", model=self.name, slots=self.slots,
                    max_seq=self.max_seq,
                    kv_cache_bytes=self.kv_cache_bytes,
                    kv_page_size=self.page_size,
                    kv_num_pages=self.num_pages,
                    prefix_cache=("on" if self.prefix_cache_enabled
                                  else "off"),
                    prefill_chunk=self.prefill_chunk,
                    admission=self.admission,
                    max_queue_requests=self.max_queue_requests,
                    external=True, **self._spec_stats())
        return self

    def dispatch_pending(self) -> Optional[float]:
        """Externally-driven decode step (fleet mode): expire queued
        deadlines, join queued prompts into free slots, advance prefill
        by at most one chunk, and advance every active stream one
        token.  Returns the wall seconds spent — the device-time the
        fleet's fair scheduler charges this tenant — or None when
        nothing was due.  Error containment matches the owned decode
        loop (a poisoned step fails the active streams, the engine
        keeps serving)."""
        t0 = self.clock()
        self._batcher.reap_expired()
        adopted = self._join_adopted()
        self._admit()
        progressed = self._prefill_step() or adopted
        self._grow_active_pages()
        if not any(s is not None and not s.prefilling
                   for s in self._slots_state):
            return max(0.0, self.clock() - t0) if progressed else None
        self._fire_slow_decode()
        try:
            self._step_active()
        except BaseException as e:  # noqa: BLE001 — same containment
            # as _decode_loop: the step's failure is the streams', not
            # the fleet dispatcher's
            self._recover_from_dispatch_error(e, "gen_decode_error")
        return max(0.0, self.clock() - t0)

    @property
    def has_pending(self) -> bool:
        """Whether the engine has work an external dispatcher should
        schedule: occupied decode slots (active or prefilling), queued
        prompts, or migrated streams awaiting adoption."""
        return (any(s is not None for s in self._slots_state)
                or self._batcher.queue_depth > 0
                or len(self._adopt_q) > 0)

    # ---- producer side -------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: int = 0,
               sampling: Optional[SamplingParams] = None,
               handoff=None) -> GenerationStream:
        """Queue one prompt (1-D int token ids) and return its
        :class:`GenerationStream`.  Thread-safe.

        ``max_new_tokens`` caps the stream (default from config);
        generation also ends at ``eos_id`` when the engine has one.
        ``deadline_ms``/``priority`` behave exactly like the serving
        engine's (PR 8): a prompt still queued at its deadline expires
        with DeadlineExceeded before any prefill is burned; under a
        full bounded queue the admission policy applies per request.

        ``sampling`` selects the request's decoding strategy
        (temperature/top-k/top-p, seeded — see
        :class:`~.sampling.SamplingParams`); None or temperature 0 is
        greedy argmax, and a batch with no sampled request dispatches
        the UNSAMPLED programs so the bit-parity pins hold exactly.

        ``handoff`` (disaggregated serving) is an optional
        ``callable(payload) -> bool`` the engine offers the stream's
        exported KV pages to at prefill completion — True migrates the
        stream to a decode engine, False/raise keeps decoding here
        (see :meth:`adopt_migrated`)."""
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise ValueError("empty prompt")
        if sampling is not None and not isinstance(sampling,
                                                   SamplingParams):
            raise TypeError(f"sampling must be a SamplingParams, "
                            f"got {type(sampling).__name__}")
        # None-check, not truthiness: an explicit 0 must hit the guard
        # below, not silently fall back to the config default
        max_new = (self.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        if arr.size + max_new > self.max_seq:
            raise ValueError(
                f"prompt ({arr.size}) + max_new_tokens ({max_new}) "
                f"exceeds the KV cache length max_seq={self.max_seq}")
        t0 = self.clock()
        self.metrics.record_submitted()
        tr = self._tracer
        trace = tr.new_trace() if tr.active else None
        stream = GenerationStream(arr.size, max_new, t0,
                                  deadlined=deadline_ms is not None,
                                  trace=trace, sampling=sampling,
                                  handoff=handoff)
        deadline = None if deadline_ms is None else t0 + deadline_ms / 1e3
        metrics = self.metrics
        trace_term = self._trace_terminal

        def on_done(out, now: float) -> bool:
            # failure-path resolution only (expiry/shed/drain/stop);
            # the success path is the decode loop's _finish
            if isinstance(out, BaseException):
                if stream._fail(out):
                    metrics.record_failure(out)
                    trace_term(stream, phase_of(out), now)
                    return True
            return False

        req = _GenRequest(stream, arr.copy(), on_done, t0,
                          deadline=deadline, priority=priority)
        req.trace = trace

        def count_cancel(f):
            # a cancel-while-QUEUED succeeds on the pending future and
            # no resolution path ever runs for it (the join claim just
            # drops the request) — count the submitted stream's
            # outcome at the cancel instant, or the submitted ==
            # outcomes reconciliation leaks one per cancel.  A
            # mid-generation cancel cannot reach here with
            # cancelled()=True (cancel() on a RUNNING future fails;
            # _retire counts it via record_failure instead).
            if f.cancelled():
                metrics.record_cancelled()
                trace_term(stream, "cancelled", self.clock())

        stream.future.add_done_callback(count_cancel)
        try:
            self._batcher.submit(req)
        except OverloadError:
            self.metrics.record_rejected()
            self._trace_terminal(stream, "rejected", self.clock())
            raise
        except RuntimeError as e:
            self.metrics.record_rejected()
            self._trace_terminal(stream, "rejected", self.clock())
            raise OverloadError(
                f"engine is not admitting new work ({e})") from e
        return stream

    def _trace_terminal(self, stream: GenerationStream, phase: str,
                        now: float) -> None:
        """Record the stream's ONE terminal `request` span (no-op for
        unsampled streams) — phase counts reconcile with the metrics
        counters exactly like the dense engine's."""
        if stream.trace is None:
            return
        self._tracer.span(
            "request", stream.trace, stream.t_submit, now,
            tid=self.name or "generate", phase=phase,
            tokens=len(stream._tokens), model=self.name)

    def _pool_stats(self) -> Dict:
        """The page-pool/prefix-cache snapshot merged into gen_stats
        and stats() — lifetime counters stay monotonic across the
        pool rebuilds a poisoned dispatch forces."""
        pool = self._pool
        prefix = self._prefix
        hw = max(self._pool_high_base, pool.high_water)
        prompt_toks = self._prompt_tokens
        return {
            "kv_page_size": self.page_size,
            "kv_num_pages": self.num_pages,
            "kv_pages_in_use": pool.pages_in_use,
            "kv_pages_high_water": hw,
            "kv_high_water_bytes":
                hw * self.kv_plan["page_bytes"]
                + self.kv_plan["state_bytes"],
            "prefix_cache": "on" if prefix is not None else "off",
            "prefix_hit_tokens": self._hit_tokens,
            "prefix_hit_rate": (round(self._hit_tokens
                                      / prompt_toks, 4)
                                if prompt_toks else 0.0),
            "prefix_pages_cached": len(prefix) if prefix else 0,
            "evictions": (self._evictions_base
                          + (prefix.evictions if prefix else 0)),
            "prefill_chunks": self._chunks_total,
        }

    def stats(self) -> Dict:
        active = sum(1 for s in self._slots_state if s is not None)
        return {**self.metrics.snapshot(), "slots": self.slots,
                "active_slots": active, "max_seq": self.max_seq,
                "kv_cache_bytes": self.kv_cache_bytes,
                "prefill_chunk": self.prefill_chunk,
                "admission": self.admission,
                "max_queue_requests": self.max_queue_requests,
                "peak_queue_requests": self._batcher.peak_rows}

    # ---- dispatcher thread ---------------------------------------------
    def _decode_loop(self) -> None:
        """One iteration per decode step: admit queued prompts into
        free slots, advance prefill by AT MOST one chunk (the
        decode-stall cap), then advance every active stream by one
        token with ONE dispatch + ONE fetch (RL010)."""
        while True:
            if self._abort.is_set():
                self._abort_active()
                return
            # expire queued deadlines at EVERY step boundary — with all
            # slots busy, _admit() never polls, and a deadline must
            # fail AT the deadline (PR 8's contract), not when a slot
            # happens to free
            self._batcher.reap_expired()
            self._join_adopted()
            self._admit()
            progressed = self._prefill_step()
            self._grow_active_pages()
            if any(s is not None and not s.prefilling
                   for s in self._slots_state):
                self._fire_slow_decode()
                try:
                    self._step_active()
                except BaseException as e:  # noqa: BLE001 — one
                    # poisoned step must fail the ACTIVE streams, not
                    # kill the dispatcher; queued prompts still served
                    self._recover_from_dispatch_error(e,
                                                      "gen_decode_error")
                continue
            if progressed or any(s is not None
                                 for s in self._slots_state):
                continue  # prefill still in flight: keep chunking
            reqs = self._batcher.next_batch(timeout=0.05)
            if reqs:
                for r in reqs:
                    self._assign(r)
                continue
            if (self._closing.is_set()
                    and self._batcher.queue_depth == 0):
                return

    def _admit(self) -> None:
        """Join queued prompts into free slots at the step boundary —
        the continuous-batching join point.  Assignment is instant
        (prefix-cache lookup + slot bookkeeping); the prefill itself
        runs chunk-by-chunk at later boundaries."""
        for slot in range(self.slots):
            if self._slots_state[slot] is not None:
                continue
            batch = self._batcher.poll()
            if not batch:
                return
            for r in batch:
                self._assign(r, slot)

    def _assign(self, req: _GenRequest,
                slot: Optional[int] = None) -> None:
        if slot is None or self._slots_state[slot] is not None:
            slot = next((i for i, s in enumerate(self._slots_state)
                         if s is None), None)
            if slot is None:
                # unreachable from the loop (joins only happen with a
                # free slot), but never strand a stream if it ever is
                req.stream._fail(SheddedError(
                    "internal: no free decode slot at join"))
                return
        stream = req.stream
        try:
            claimed = stream.future.set_running_or_notify_cancel()
        except RuntimeError:
            claimed = False
        if not claimed:
            return  # cancelled/expired while queued (the cancel was
            #         counted at cancel() time — see submit())
        prompt = req.xs[0]
        hits: List[int] = []
        if self._prefix is not None:
            hits = self._prefix.lookup(prompt)
        st = _Slot(stream, prompt, hits, self.page_size, self.clock())
        for i, pg in enumerate(hits):
            self._table[slot, i] = pg
        self._slots_state[slot] = st
        self._prefill_q.append((slot, st))
        self._prompt_tokens += int(prompt.size)
        self._hit_tokens += st.hit_tokens

    # ---- paged prefill (chunked) ---------------------------------------
    def _prefill_step(self) -> bool:
        """Advance prefill by AT MOST one chunk dispatch per step
        boundary (Sarathi-style): a long joining prompt stalls
        in-flight decode by one bounded chunk, never one monolithic
        prompt.  Returns True when a chunk (or a prefill-side
        retirement) happened."""
        while self._prefill_q:
            slot, st = self._prefill_q[0]
            if self._slots_state[slot] is not st or not st.prefilling:
                self._prefill_q.popleft()  # slot retired/reassigned
                continue
            if st.stream.cancelled:
                # cancel landed between chunks (or between the claim
                # and the first chunk): free the slot AND its pages
                # without burning another dispatch
                self._prefill_q.popleft()
                self._fail_slot(slot, st, GenerationCancelled(
                    f"stream cancelled during prefill after "
                    f"{st.chunks} chunk(s); KV slot {slot} and "
                    f"{len(st.pages)} page(s) freed"), "cancelled")
                return True
            return self._run_chunk(slot, st)
        return False

    def _run_chunk(self, slot: int, st: _Slot) -> bool:
        """Dispatch ONE prefill chunk for the queue-head slot; on the
        final chunk, fetch the stream's first token (the one host sync
        per join), activate the slot, and promote its full prompt
        pages into the prefix cache."""
        prompt = st.prompt
        start = st.next_pos
        remaining = int(prompt.size) - start
        chunk = (remaining if self.prefill_chunk <= 0
                 else min(self.prefill_chunk, remaining))
        if not self._ensure_pages(slot, st, start + chunk):
            self._prefill_q.popleft()
            self._fail_slot(slot, st, KVCacheExhausted(
                f"no KV page free for prefill at position {start} "
                f"(pool {self.num_pages} pages, "
                f"{self._pool.pages_in_use} in use, prefix cache "
                f"fully referenced)"), "shed")
            return True
        bucket = self._decoder.prefill_bucket(chunk)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :chunk] = prompt[start:start + chunk]
        fn = self._decoder.prefill_fn(bucket)
        final = start + chunk >= int(prompt.size)
        tok = 0
        try:
            with jax.profiler.StepTraceAnnotation(
                    "gen-prefill", step_num=self._n_steps):
                first, self._caches = fn(
                    self._params, self._caches, tokens,
                    self._table[slot].copy(), np.int32(slot),
                    np.int32(start), np.int32(chunk))
                if final:
                    # one fetch per JOIN (not per chunk): the stream's
                    # first token comes out of the last chunk itself
                    tok = int(jax.device_get(first))
        except BaseException as e:  # noqa: BLE001 — a poisoned chunk
            # fails the joining stream AND (because the dispatch may
            # have consumed the donated cache pytree) every in-flight
            # stream; the engine re-arms and keeps serving the queue
            self._prefill_q.popleft()
            if st.stream._fail(e):
                self.metrics.record_failure(e)
                self._trace_terminal(st.stream, "error", self.clock())
            self._recover_from_dispatch_error(e, "gen_prefill_error")
            return True
        st.next_pos = start + chunk
        st.chunks += 1
        self._chunks_total += 1
        if not final:
            return True  # next chunk at a later step boundary
        self._prefill_q.popleft()
        now = self.clock()
        st.prefilling = False
        st.length = int(prompt.size)
        st.last_token = tok
        st.generated = 1
        stream = st.stream
        stream.ttft = now - stream.t_submit
        stream._emit(tok)
        self.metrics.record_ttft(stream.ttft)
        self.metrics.record_prefill_token()
        if self._prefix is not None:
            # promote the freshly-computed full prompt pages (the hit
            # prefix re-touches its nodes' LRU stamps)
            full = max(0, (int(prompt.size) - 1) // self.page_size)
            self._prefix.insert(prompt, st.pages[:full])
        if self._tracer.active and stream.trace is not None:
            tname = self.name or "generate"
            self._tracer.span("queue", stream.trace, stream.t_submit,
                              st.t_join, tid=tname, slot=slot)
            self._tracer.span("prefill", stream.trace, st.t_join, now,
                              tid=tname, slot=slot, phase="target",
                              prompt_len=int(prompt.size),
                              prefix_hit_tokens=st.hit_tokens,
                              prefill_chunks=st.chunks)
        if stream.handoff is not None and not (
                st.generated >= stream.max_new
                or (self.eos_id is not None and tok == self.eos_id)):
            # disaggregated serving: offer the freshly-prefilled KV
            # page chain to the router's handoff.  Streams retiring at
            # this very boundary (max_new=1, first token is EOS) stay
            # local — migrating them would ship pages nothing decodes.
            if self._migrate_out(slot, st, now):
                return True
        if self._spec_active():
            self._draft_prefill(slot, st)
        self._retire(slot, st, now)
        return True

    # ---- disaggregated prefill/decode migration ------------------------
    def _migrate_out(self, slot: int, st: _Slot, now: float) -> bool:
        """Export the slot's KV pages + stream state and offer them to
        ``stream.handoff``.  True = a decode engine adopted the stream:
        the source frees the slot (shared prefix pages stay cached —
        the trie holds its own references).  False = fallback to
        co-located decode with ONE ``serve_health`` event and NO stream
        failed — the slot is untouched either way until adoption is
        confirmed."""
        stream = st.stream
        t0 = self.clock()
        try:
            if not (self._decoder.has_attention
                    and self._decoder.supports_chunking):
                raise RuntimeError(
                    "graph state is not pageable (no paged attention): "
                    "KV migration needs a chunkable attention graph")
            e0 = time.perf_counter()
            host = export_pages(self._caches, st.pages, self.num_pages,
                                pad_to=self._decoder.pages_per_slot)
            self.migrate_export_ms.append(
                (time.perf_counter() - e0) * 1e3)
            # charge only the REAL chain (the pad rows are a fixed-
            # shape compile-cache artifact, not shipped state)
            nbytes = sum(int(a.nbytes) // int(a.shape[0])
                         for sub in host.values()
                         for a in sub.values()) * len(st.pages)
            payload = {
                "stream": stream,
                "prompt": st.prompt,
                "pages": host,
                "pages_used": len(st.pages),
                "nbytes": nbytes,
                "page_size": self.page_size,
                "last_token": int(st.last_token),
                "length": int(st.length),
                "generated": int(st.generated),
                "source": self.name,
            }
            adopted = bool(stream.handoff(payload))
        except BaseException as e:  # noqa: BLE001 — a failed export or
            # handoff must cost this stream NOTHING but staying local
            self._migrate_fallback(slot, e)
            return False
        if not adopted:
            self._migrate_fallback(slot, None)
            return False
        if self._tracer.active and stream.trace is not None:
            self._tracer.span("migrate", stream.trace, t0, self.clock(),
                              tid=self.name or "generate", slot=slot,
                              phase="export", pages=len(st.pages),
                              bytes=payload["nbytes"])
        # the destination owns the stream now: free the slot WITHOUT
        # finishing it.  release() drops the slot's references only —
        # prefix pages the trie promoted stay resident here, so a
        # same-prefix prompt still hits.
        self._release_slot(slot, st)
        return True

    def _migrate_fallback(self, slot: int, exc) -> None:
        """Migration declined/failed: one health event (mirror of
        ``_spec_demote`` — NO stream fails, decode continues
        co-located on this engine) plus a flight dump when it was an
        error rather than a routing decision."""
        err = ("" if exc is None
               else f"{type(exc).__name__}: {exc}"[:300])
        get_logger("serve").event(
            "serve_health", model=self.name, component="migration",
            status="fallback", slot=slot,
            reason=("handoff_declined" if exc is None
                    else "handoff_error"),
            error=err, step=self._n_steps)
        if exc is not None:
            flight_dump("gen_migrate_error",
                        extra={"model": self.name, "slot": slot,
                               "error": err, "step": self._n_steps})

    def adopt_migrated(self, payload: Dict) -> bool:
        """Decode-engine side of migration: enqueue an
        :func:`~.pages.export_pages` payload (plus stream state) for
        adoption at this engine's next dispatch boundary.  Thread-safe
        (the source engine's dispatcher calls this through the router's
        handoff): the payload is host-only data and the deque append is
        atomic — the import itself runs on THIS engine's dispatch
        thread, which owns the pool/caches (single-writer
        discipline)."""
        with self._lifecycle:
            if self._stopped or self._closing.is_set():
                return False
        self._adopt_q.append(payload)
        return True

    def _join_adopted(self) -> bool:
        """Import ONE queued migrated stream into a free slot
        (dispatcher thread).  A payload with no free slot waits at the
        queue head — slots free as streams retire.  One adoption per
        dispatch boundary bounds the decode-step gap co-hosted streams
        pay for an arriving migration burst by a single import; the
        queue drains across consecutive turns (``has_pending`` keeps
        the dispatcher coming back).  Returns True when a stream
        joined."""
        if not self._adopt_q:
            return False
        if not any(s is None for s in self._slots_state):
            return False
        try:
            payload = self._adopt_q.popleft()
        except IndexError:
            return False
        self._import_migrated(payload)
        return True

    def _import_migrated(self, payload: Dict) -> None:
        """Allocate destination pages, scatter the payload in with one
        ``device_put`` (:func:`~.pages.import_pages`), and seat the
        stream in a free slot mid-generation — decode continues here
        bit-for-bit where the source's prefill left off.  The prompt's
        full pages are promoted into THIS engine's prefix trie (the
        accounting parity with a co-located join); pool exhaustion is
        the same legitimate shed as a co-located allocation failure."""
        stream: GenerationStream = payload["stream"]
        prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
        now = self.clock()
        slot = next((i for i, s in enumerate(self._slots_state)
                     if s is None), None)
        if slot is None:  # _join_adopted guards this; never strand
            self._adopt_q.appendleft(payload)
            return
        first = next(iter(next(iter(payload["pages"].values())).values()))
        need = int(payload.get("pages_used") or first.shape[0])
        pages: List[int] = []
        while len(pages) < need:
            pg = self._alloc_page()
            if pg is None:
                break
            pages.append(pg)
        if len(pages) < need or int(payload["page_size"]) != \
                self.page_size:
            for pg in pages:
                self._pool.release(pg)
            exc = KVCacheExhausted(
                f"cannot adopt migrated stream: need {need} page(s) "
                f"of size {payload['page_size']} (pool {self.num_pages} "
                f"pages of {self.page_size}, {self._pool.pages_in_use} "
                f"in use)")
            if stream._fail(exc):
                self.metrics.record_failure(exc)
                self._trace_terminal(stream, "shed", now)
            return
        try:
            i0 = time.perf_counter()
            self._caches = import_pages(self._caches, payload["pages"],
                                        pages)
            self.migrate_import_ms.append(
                (time.perf_counter() - i0) * 1e3)
        except BaseException as e:  # noqa: BLE001 — a poisoned import
            # fails only the migrating stream (import_pages validates
            # every leaf BEFORE its first donating scatter, so a graph
            # or geometry mismatch leaves the resident pool untouched)
            for pg in pages:
                self._pool.release(pg)
            if stream._fail(e):
                self.metrics.record_failure(e)
                self._trace_terminal(stream, "error", now)
            return
        st = _Slot(stream, prompt, [], self.page_size, now)
        st.hit_tokens = 0
        st.pages = pages
        st.prefilling = False
        st.length = int(payload["length"])
        st.next_pos = st.length
        st.last_token = int(payload["last_token"])
        st.generated = int(payload["generated"])
        for i, pg in enumerate(pages):
            self._table[slot, i] = pg
        self._slots_state[slot] = st
        if self._tracer.active and stream.trace is not None:
            self._tracer.span("migrate", stream.trace, now, self.clock(),
                              tid=self.name or "generate", slot=slot,
                              phase="import", pages=len(pages),
                              bytes=int(payload.get("nbytes", 0)),
                              source=str(payload.get("source", "")))
        if self._prefix is not None:
            full = max(0, (int(prompt.size) - 1)) // self.page_size
            self._prefix.insert(prompt, st.pages[:full])
        if self._spec_active():
            # speculative decoding composes with disaggregation by
            # co-hosting the draft with the DECODE engine: mirror the
            # prompt into the draft cache exactly like a local join
            self._draft_prefill(slot, st)

    # ---- page bookkeeping ----------------------------------------------
    def _alloc_page(self) -> Optional[int]:
        """One page from the pool, LRU-evicting unreferenced prefix
        pages under pressure; None only when every page backs a live
        slot (the caller sheds the stream)."""
        pg = self._pool.alloc()
        while pg is None and self._prefix is not None \
                and self._prefix.evict(1):
            pg = self._pool.alloc()
        return pg

    def _ensure_pages(self, slot: int, st: _Slot,
                      upto_pos: int) -> bool:
        """Grow the slot's page table to cover positions
        ``[0, upto_pos)``.  The whole deficit is evicted in ONE trie
        walk up front (PrefixCache.evict batches the LRU scan) — a
        per-allocation evict_one loop would rescan the trie per page
        under exactly the pool pressure that makes the trie large."""
        need = (int(upto_pos) - 1) // self.page_size + 1
        deficit = need - len(st.pages) - self._pool.pages_free
        if deficit > 0 and self._prefix is not None:
            self._prefix.evict(deficit)
        while len(st.pages) < need:
            pg = self._alloc_page()
            if pg is None:
                return False
            self._table[slot, len(st.pages)] = pg
            st.pages.append(pg)
        return True

    def _grow_active_pages(self) -> None:
        """Before a decode dispatch: every active slot needs a page for
        the position it is about to write.  A slot the pool cannot
        serve (undersized ``serve_kv_pages`` with the prefix cache
        fully referenced) is shed — only that stream fails."""
        for i, s in enumerate(self._slots_state):
            if s is None or s.prefilling:
                continue
            if not self._ensure_pages(i, s, s.length + 1):
                self._fail_slot(i, s, KVCacheExhausted(
                    f"no KV page free for decode at position "
                    f"{s.length} (pool {self.num_pages} pages, "
                    f"{self._pool.pages_in_use} in use)"), "shed")

    def _release_slot(self, slot: int, st: _Slot) -> None:
        """Return the slot's pages to the pool (shared prefix pages
        just drop one reference — the trie keeps them cached) and
        clear its table row back to the OOB sentinel."""
        for pg in st.pages:
            self._pool.release(pg)
        st.pages = []
        self._table[slot, :] = self._pool.no_page
        if self._draft_pool is not None:
            for pg in st.draft_pages:
                self._draft_pool.release(pg)
            self._draft_table[slot, :] = self._draft_pool.no_page
        st.draft_pages = []
        self._slots_state[slot] = None

    def _fail_slot(self, slot: int, st: _Slot, exc: BaseException,
                   phase: str) -> None:
        now = self.clock()
        if st.stream._fail(exc):
            self.metrics.record_failure(exc)
            self._trace_terminal(st.stream, phase, now)
        self._release_slot(slot, st)

    # ---- decode --------------------------------------------------------
    def _step_active(self) -> None:
        """Advance every active stream one boundary: a speculative
        draft+verify ROUND when a live draft is attached, else one
        plain decode step.  Callers wrap this in the dispatch-error
        containment."""
        if self._spec_active():
            self._spec_decode_once()
        else:
            self._decode_once()

    def _spec_active(self) -> bool:
        return self._spec_on and self._spec_gamma >= 2

    def _batch_sampling(self) -> bool:
        """Whether ANY active slot carries a non-greedy strategy — the
        routing bit: all-greedy batches dispatch the UNSAMPLED programs
        so the bit-parity pins never depend on the sampled kernels."""
        for s in self._slots_state:
            if s is None or s.prefilling or s.stream.sampling is None:
                continue
            if not s.stream.sampling.is_greedy:
                return True
        return False

    def _sampling_arrays(self):
        """Per-slot strategy arrays for the sampled programs; inactive
        and greedy slots ride the defaults (temp 0 -> exact one-hot
        argmax inside the kernel)."""
        temp = np.zeros((self.slots,), np.float32)
        top_k = np.zeros((self.slots,), np.int32)
        top_p = np.ones((self.slots,), np.float32)
        seeds = np.zeros((self.slots,), np.int32)
        for i, s in enumerate(self._slots_state):
            if s is None or s.prefilling or s.stream.sampling is None:
                continue
            sp = s.stream.sampling
            temp[i] = sp.temperature
            top_k[i] = sp.top_k
            top_p[i] = sp.top_p
            seeds[i] = sp.seed
        return temp, top_k, top_p, seeds

    def _decode_once(self) -> None:
        """Advance the whole decode batch one position: one dispatch,
        one token fetch, scatter to streams.  Write pages/rows are
        host-computed — inactive and PREFILLING slots ride the pool's
        OOB sentinel so their dummy writes drop instead of corrupting
        a (possibly shared) page."""
        tokens = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        wp = np.full((self.slots,), self._pool.no_page, np.int32)
        wr = np.zeros((self.slots,), np.int32)
        nactive = 0
        for i, s in enumerate(self._slots_state):
            if s is not None and not s.prefilling:
                tokens[i] = s.last_token
                pos[i] = s.length
                wp[i] = self._table[i, s.length // self.page_size]
                wr[i] = s.length % self.page_size
                nactive += 1
        sampled = self._batch_sampling()
        # ONE lock-free tracing check per decode step (hot-path
        # contract, docs/observability.md)
        traced = self._tracer.active
        t0 = self.clock()
        with jax.profiler.StepTraceAnnotation("generate",
                                              step_num=self._n_steps):
            if sampled:
                temp, top_k, top_p, seeds = self._sampling_arrays()
                fn = self._decoder.decode_sampled_fn()
                nxt, self._caches = fn(
                    self._params, self._caches, tokens, pos,
                    self._table.copy(), wp, wr, temp, top_k, top_p,
                    seeds)
            else:
                fn = self._decoder.decode_fn()
                nxt, self._caches = fn(self._params, self._caches,
                                       tokens, pos, self._table.copy(),
                                       wp, wr)
            # THE one host sync per decode step for the whole batch —
            # per-stream tokens are scattered from it below (RL010)
            host = np.asarray(jax.device_get(nxt))
        now = self.clock()
        self._n_steps += 1
        for i, s in enumerate(self._slots_state):
            if s is None or s.prefilling:
                continue
            tok = int(host[i])
            s.length += 1
            s.generated += 1
            s.last_token = tok
            s.stream._emit(tok)
            self._retire(i, s, now)
        if traced:
            self._tracer.span("decode_step", None, t0, now,
                              tid=self.name or "generate",
                              step=self._n_steps - 1, active=nactive,
                              phase="decode")
        self.metrics.record_decode_step(nactive, now - t0)
        self._fire_cancel_at_token(now)
        if self.stats_every and self._n_steps % self.stats_every == 0:
            self.metrics.emit(extra={"slots": self.slots,
                                     "active": nactive})

    # ---- speculative round ---------------------------------------------
    def _spec_decode_once(self) -> None:
        """One speculative ROUND for the whole batch: the draft scans
        γ decode steps in ONE dispatch, the target verifies the whole
        window in ONE dispatch (the slot-batched chunked-prefill
        kernel), and ONE host fetch brings back the accept counts plus
        the emit-ready token rows — 2 dispatches + 1 sync per up-to-γ
        tokens, vs γ of each for plain decode (RL010's budget, spent
        better).

        No rollback state: ``out[i, :min(n+1, γ)]`` is emitted verbatim
        (accepted proposals then the correction), the draft cache is
        exactly caught up after every round by construction (the
        no-bonus window), and rows written beyond the accept point stay
        invisible behind the causal mask until overwritten.  Trailing
        pages past the accepted length go back to the pools
        immediately."""
        g = self._spec_gamma
        # provision BOTH pools for the whole window up front; positions
        # past max_seq ride the sentinel (their writes drop, and the
        # prompt+max_new<=max_seq budget retires the stream before any
        # such row could be emitted)
        for i, s in enumerate(self._slots_state):
            if s is None or s.prefilling:
                continue
            upto = min(s.length + g, self.max_seq)
            if not self._ensure_pages(i, s, upto):
                self._fail_slot(i, s, KVCacheExhausted(
                    f"no KV page free for a γ={g} verify window at "
                    f"position {s.length} (pool {self.num_pages} "
                    f"pages, {self._pool.pages_in_use} in use)"),
                    "shed")
                continue
            if not self._ensure_draft_pages(i, s, upto):
                self._fail_slot(i, s, KVCacheExhausted(
                    f"no DRAFT KV page free at position {s.length} "
                    f"(draft pool {self.num_pages} pages, "
                    f"{self._draft_pool.pages_in_use} in use)"), "shed")
        active = [(i, s) for i, s in enumerate(self._slots_state)
                  if s is not None and not s.prefilling]
        if not active:
            return
        nactive = len(active)
        tokens = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        vwp = np.full((self.slots, g), self._pool.no_page, np.int32)
        vwr = np.zeros((self.slots, g), np.int32)
        dwp = np.full((g, self.slots), self._draft_pool.no_page,
                      np.int32)
        dwr = np.zeros((g, self.slots), np.int32)
        for i, s in active:
            tokens[i] = s.last_token
            pos[i] = s.length
            for t in range(g):
                p = s.length + t
                if p >= self.max_seq:
                    break  # sentinel stays: the write drops
                vwp[i, t] = self._table[i, p // self.page_size]
                vwr[i, t] = p % self.page_size
                dwp[t, i] = self._draft_table[i, p // self.page_size]
                dwr[t, i] = p % self.page_size
        sampled = self._batch_sampling()
        if sampled:
            temp, top_k, top_p, seeds = self._sampling_arrays()
        traced = self._tracer.active
        t0 = self.clock()
        try:
            self._fire_spec_draft_fail()
            with jax.profiler.StepTraceAnnotation(
                    "gen-draft", step_num=self._n_steps):
                if sampled:
                    dfn = self._draft_decoder.draft_fn(g, sampled=True)
                    (d, q), self._draft_caches = dfn(
                        self._draft_params, self._draft_caches,
                        tokens, pos, self._draft_table.copy(), dwp,
                        dwr, temp, top_k, top_p, seeds)
                else:
                    dfn = self._draft_decoder.draft_fn(g)
                    d, self._draft_caches = dfn(
                        self._draft_params, self._draft_caches,
                        tokens, pos, self._draft_table.copy(), dwp,
                        dwr)
        except BaseException as e:  # noqa: BLE001 — draft-side only:
            # the TARGET caches were never touched, so no stream fails;
            # demote and decode this boundary plain
            self._spec_demote("draft_error", e)
            self._decode_once()
            return
        t1 = self.clock()
        if traced:
            self._tracer.span("decode_step", None, t0, t1,
                              tid=self.name or "generate",
                              step=self._n_steps, phase="draft",
                              gamma=g, active=nactive)
        # verify failures propagate to the caller's containment: the
        # donated target caches are poisoned, so _recover_from_
        # dispatch_error must fail the streams and rebuild everything
        vfn = self._decoder.verify_fn(g, sampled=sampled)
        with jax.profiler.StepTraceAnnotation(
                "generate", step_num=self._n_steps):
            if sampled:
                (n_acc, out), self._caches = vfn(
                    self._params, self._caches, tokens, d, q,
                    pos, self._table.copy(), vwp, vwr, temp, top_k,
                    top_p, seeds)
            else:
                (n_acc, out), self._caches = vfn(
                    self._params, self._caches, tokens, d, pos,
                    self._table.copy(), vwp, vwr)
            # THE one host sync per round for the whole batch (RL010):
            # accept counts + the emit-ready token rows together
            n_host, out_host = jax.device_get((n_acc, out))
        n_host = np.asarray(n_host)
        out_host = np.asarray(out_host)
        now = self.clock()
        self._n_steps += 1
        emitted = proposed = accepted = 0
        for i, s in active:
            n = int(n_host[i])
            proposed += g
            accepted += n
            # rows < n are the accepted proposals; row n (when < γ) is
            # the verifier's correction — emit in order, stopping
            # EXACTLY where the sequential engine stops (EOS /
            # max_new can land mid-window)
            for t in range(min(n + 1, g)):
                tok = int(out_host[i, t])
                s.length += 1
                s.generated += 1
                s.last_token = tok
                s.stream._emit(tok)
                emitted += 1
                if s.generated >= s.stream.max_new or (
                        self.eos_id is not None
                        and tok == self.eos_id):
                    break
            self._trim_slot_pages(i, s)
            self._retire(i, s, now)
        if traced:
            self._tracer.span("decode_step", None, t1, now,
                              tid=self.name or "generate",
                              step=self._n_steps - 1, phase="verify",
                              gamma=g, active=nactive,
                              proposed=proposed, accepted=accepted)
        self.metrics.record_spec_round(proposed, accepted)
        # TPOT percentiles become per-ROUND walls here (documented in
        # GenerationMetrics.snapshot); tokens_per_s stays comparable
        self.metrics.record_decode_step(emitted, now - t0)
        self._spec_account(g, proposed, accepted, now - t0)
        self._fire_cancel_at_token(now)
        if self.stats_every and self._n_steps % self.stats_every == 0:
            self.metrics.emit(extra={"slots": self.slots,
                                     "active": nactive})

    def _ensure_draft_pages(self, slot: int, st: _Slot,
                            upto_pos: int) -> bool:
        """Grow the slot's DRAFT page table to cover positions
        ``[0, upto_pos)`` — same geometry as the target's, but no
        prefix sharing (draft rows are never promoted to the trie) and
        so no eviction pressure valve."""
        need = (int(upto_pos) - 1) // self.page_size + 1
        while len(st.draft_pages) < need:
            pg = self._draft_pool.alloc()
            if pg is None:
                return False
            self._draft_table[slot, len(st.draft_pages)] = pg
            st.draft_pages.append(pg)
        return True

    def _trim_slot_pages(self, slot: int, st: _Slot) -> None:
        """Release the trailing pages a partially-accepted window
        provisioned past the accept point, in BOTH pools — the
        page-granular rollback (rejected rows inside kept pages need no
        rollback at all: the causal mask hides them until the next
        round overwrites them).  Released target pages sit strictly
        after the shared prompt prefix (length >= prompt.size), so
        their refcount is 1 and they return to the pool for real."""
        keep = st.length // self.page_size + 1
        while len(st.pages) > keep:
            pg = st.pages.pop()
            self._table[slot, len(st.pages)] = self._pool.no_page
            self._pool.release(pg)
        while len(st.draft_pages) > keep:
            pg = st.draft_pages.pop()
            self._draft_table[slot, len(st.draft_pages)] = \
                self._draft_pool.no_page
            self._draft_pool.release(pg)

    def _draft_prefill(self, slot: int, st: _Slot) -> None:
        """Mirror a freshly-joined stream's prompt into the DRAFT cache
        with ONE monolithic prefill dispatch (no chunking, no prefix
        sharing — draft rows are private, and the draft is a fraction
        of the target so one chunk is cheap).  No host sync: the
        draft's own next-token argmax is unused — round 0 scans from
        the TARGET's real first token.  Any draft-side failure demotes
        speculation; the stream itself is untouched."""
        prompt = st.prompt
        size = int(prompt.size)
        if st.generated >= st.stream.max_new or (
                self.eos_id is not None
                and st.last_token == self.eos_id):
            return  # retiring at this boundary: no draft rows needed
        try:
            if not self._ensure_draft_pages(slot, st, size):
                raise KVCacheExhausted(
                    f"no draft KV page free for a {size}-token prompt "
                    f"({self._draft_pool.pages_in_use} of "
                    f"{self.num_pages} in use)")
            bucket = self._draft_decoder.prefill_bucket(size)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :size] = prompt
            fn = self._draft_decoder.prefill_fn(bucket)
            t0 = self.clock()
            with jax.profiler.StepTraceAnnotation(
                    "gen-draft-prefill", step_num=self._n_steps):
                _, self._draft_caches = fn(
                    self._draft_params, self._draft_caches,
                    tokens, self._draft_table[slot].copy(),
                    np.int32(slot), np.int32(0), np.int32(size))
            if self._tracer.active and st.stream.trace is not None:
                self._tracer.span("prefill", st.stream.trace, t0,
                                  self.clock(),
                                  tid=self.name or "generate",
                                  slot=slot, phase="draft",
                                  prompt_len=size)
        except BaseException as e:  # noqa: BLE001 — draft-side only:
            # demote and keep serving plain; the target stream already
            # has its first token
            self._spec_demote("draft_prefill_error", e)

    def _spec_account(self, g: int, proposed: int, accepted: int,
                      wall: float) -> None:
        """Post-round controller bookkeeping: accept-rate EWMA, per-γ
        round-cost EWMA, the accept-collapse guard, and (adaptive
        policy) the periodic γ re-pricing."""
        self._spec_rounds += 1
        self._spec_seen_proposed += proposed
        a = self._SPEC_EWMA_ALPHA
        if proposed:
            rate = accepted / proposed
            self._accept_ewma = (
                rate if self._accept_ewma is None
                else (1 - a) * self._accept_ewma + a * rate)
        prev = self._spec_costs.get(g)
        self._spec_costs[g] = (wall if prev is None
                               else (1 - a) * prev + a * wall)
        if (self._spec_seen_proposed >= self._SPEC_COLLAPSE_MIN_PROPOSED
                and self._accept_ewma is not None
                and self._accept_ewma < self._SPEC_COLLAPSE_ACCEPT):
            # a useless draft burns a dispatch per round for ~nothing —
            # the engine is FASTER without it
            self._spec_demote("accept_collapse", None)
            return
        if (self.spec_policy == "adaptive"
                and len(self._spec_candidates) > 1
                and self._spec_rounds % self._SPEC_RETUNE_EVERY == 0):
            self._spec_gamma = self._spec_retune()

    def _spec_retune(self) -> int:
        """Price each candidate γ with the live accept-rate EWMA α and
        its calibrated round-wall EWMA (warmup-seeded, live-updated):
        expected emitted tokens per round is ``(1 - α^γ) / (1 - α)``
        (accepted prefix + correction, no bonus token), so the winner
        maximizes that over its cost — the gen_stats feedback loop
        pricing depth like the SOAP cost model prices strategies."""
        alpha = self._accept_ewma if self._accept_ewma is not None \
            else 0.5
        alpha = min(0.999, max(0.001, alpha))
        best, best_rate = self._spec_gamma, -1.0
        for g in self._spec_candidates:
            cost = self._spec_costs.get(g)
            if not cost or cost <= 0:
                continue
            exp_tokens = (1.0 - alpha ** g) / (1.0 - alpha)
            rate = exp_tokens / cost
            if rate > best_rate:
                best, best_rate = g, rate
        return best

    def _spec_demote(self, reason: str, exc) -> None:
        """Demote to plain decode for the rest of the engine's
        lifetime: drop the draft pool/table/caches (their HBM frees),
        count the fallback, emit ONE serve_health event.  NO stream
        fails — the target's state is untouched; every active stream
        keeps generating plain from exactly where it is."""
        if not self._spec_on:
            return
        self._spec_on = False
        self._spec_gamma = 0
        self._draft_caches = None
        self._draft_pool = None
        self._draft_table = None
        self.draft_kv_cache_bytes = 0
        for s in self._slots_state:
            if s is not None:
                s.draft_pages = []
        self.metrics.record_spec_fallback()
        get_logger("serve").event(
            "serve_health", model=self.name, component="speculation",
            status="fallback", reason=reason,
            error=("" if exc is None
                   else f"{type(exc).__name__}: {exc}"[:300]),
            step=self._n_steps,
            accept_ewma=(round(self._accept_ewma, 4)
                         if self._accept_ewma is not None else None))

    def _spec_stats(self) -> Dict:
        """The live speculation view merged into gen_stats/stats():
        off (no draft configured) / on / fallback (demoted)."""
        state = ("off" if self.draft_model is None
                 else ("on" if self._spec_on else "fallback"))
        return {
            "spec": state,
            "spec_gamma": self._spec_gamma,
            "spec_policy": self.spec_policy,
            "draft_kv_cache_bytes": self.draft_kv_cache_bytes,
        }

    def _recover_from_dispatch_error(self, e: BaseException,
                                     event: str) -> None:
        """A failed prefill/decode dispatch raised AFTER the cache
        pytree was donated: off-CPU the pool buffers are invalidated,
        so every active stream's state — and every cached prefix page
        — is unrecoverable.  Fail them all, rebuild the pool + prefix
        cache (lifetime counters carry over), reallocate the device
        pools, and keep serving queued prompts (the engine recovers; a
        poisoned dispatch must never wedge it on 'Array has been
        deleted' forever)."""
        failed = 0
        now = self.clock()
        for i, s in enumerate(self._slots_state):
            if s is None:
                continue
            if s.stream._fail(e):
                self.metrics.record_failure(e)
                self._trace_terminal(s.stream, "error", now)
                failed += 1
            self._slots_state[i] = None
        self._prefill_q.clear()
        if self._prefix is not None:
            self._evictions_base += self._prefix.evictions
        self._pool_high_base = max(self._pool_high_base,
                                   self._pool.high_water)
        self._pool = KVPagePool(self.num_pages, self.page_size)
        self._prefix = (PrefixCache(self._pool)
                        if self.prefix_cache_enabled else None)
        self._table = np.full((self.slots,
                               self._decoder.pages_per_slot),
                              self._pool.no_page, np.int32)
        self._caches = self._decoder.init_cache()
        if self._spec_on:
            # the draft's pool/table/caches are re-armed with the
            # target's: the failed round may have donated either side,
            # and the slots they described are gone regardless
            self._draft_pool = KVPagePool(self.num_pages,
                                          self.page_size)
            self._draft_table = np.full(
                (self.slots, self._draft_decoder.pages_per_slot),
                self._draft_pool.no_page, np.int32)
            self._draft_caches = self._draft_decoder.init_cache()
        get_logger("serve").event(  # RL011-ok: gen_decode_error |
            # gen_prefill_error, both declared in obs/events.py —
            # callers pass the literal
            event, model=self.name, step=self._n_steps,
            error=f"{type(e).__name__}: {e}"[:300],
            failed_streams=failed)
        # generation's dispatch-error flight trigger (no-op unless
        # FF_FLIGHT_DIR is set)
        flight_dump(event, extra={"model": self.name,
                                  "step": self._n_steps,
                                  "error": f"{type(e).__name__}: {e}"[:300],
                                  "failed_streams": failed})

    def _retire(self, slot: int, s: _Slot, now: float) -> None:
        """Free the slot — and its pages — if its stream finished or
        was cancelled; run at every step boundary, so a mid-generation
        cancel frees KV capacity for the next queued prompt
        immediately."""
        if s.stream.cancelled:
            exc = GenerationCancelled(
                f"stream cancelled after {s.generated} token(s); "
                f"KV slot {slot} and {len(s.pages)} page(s) freed")
            self._fail_slot(slot, s, exc, "cancelled")
            return
        done = s.generated >= s.stream.max_new or (
            self.eos_id is not None and s.last_token == self.eos_id)
        if done:
            if s.stream._finish():
                self.metrics.record_request(now - s.stream.t_submit,
                                            deadlined=s.stream.deadlined)
                self._trace_terminal(s.stream, "completed", now)
            self._release_slot(slot, s)

    def _abort_active(self) -> None:
        """drain(timeout) expired: shed whatever is still decoding or
        prefilling (pages go back to the pool with the slots)."""
        now = self.clock()
        for i, s in enumerate(self._slots_state):
            if s is None:
                continue
            exc = SheddedError(
                "engine drained mid-generation (drain timeout)")
            if s.stream._fail(exc):
                self.metrics.record_failure(exc)
                self._trace_terminal(s.stream, "shed", now)
            self._release_slot(i, s)
        self._prefill_q.clear()
        while self._adopt_q:
            try:
                payload = self._adopt_q.popleft()
            except IndexError:
                break
            exc = SheddedError(
                "engine drained before adopting a migrated stream")
            if payload["stream"]._fail(exc):
                self.metrics.record_failure(exc)
                self._trace_terminal(payload["stream"], "shed", now)

    # ---- fault injection (FF_FAULT generation kinds) -------------------
    def _fire_slow_decode(self) -> None:
        for st in self._gen_faults:
            if st["kind"] == "serve_slow_decode" and st["fired"] < st["n"]:
                st["fired"] += 1
                self._sleep(st["ms"] / 1e3)

    def _fire_spec_draft_fail(self) -> None:
        """``FF_FAULT=spec_draft_fail:N`` — the Nth draft dispatch
        raises (once), exercising the demote-to-plain-decode path: the
        serve_health fallback event fires and NO stream fails."""
        for st in self._gen_faults:
            if st["kind"] == "spec_draft_fail" and not st["fired"] \
                    and self._spec_rounds + 1 >= st["n"]:
                st["fired"] = 1
                raise RuntimeError(
                    f"FF_FAULT spec_draft_fail: injected draft "
                    f"failure at round {self._spec_rounds + 1}")

    def _fire_cancel_at_token(self, now: float) -> None:
        for st in self._gen_faults:
            if st["kind"] != "serve_cancel_at_token" or st["fired"]:
                continue
            for i, s in enumerate(self._slots_state):
                if s is not None and not s.prefilling \
                        and s.generated >= st["n"]:
                    st["fired"] = 1
                    get_logger("serve").event(
                        "gen_fault_cancel", model=self.name, slot=i,
                        generated=s.generated, at_token=st["n"])
                    s.stream.cancel()
                    self._retire(i, s, now)
                    break

    # ---- strategy-sharded construction ---------------------------------
    @classmethod
    def from_strategy(cls, model, strategy_file: str, mesh=None,
                      **kwargs) -> "GenerationEngine":
        """Build a tensor-parallel generation engine from a searched
        strategy ``.pb``: load the per-op ParallelConfigs, compile the
        model against them (ffcheck-verified, mesh inferred from the
        strategy when not given), place/re-place every parameter under
        its strategy PartitionSpec, and shard the KV page pools' head
        dim over the ``c`` axis — one checkpoint, any searched
        sharding.

        Accepts a fresh (uncompiled) model — compiled+initialized here
        — or an already-initialized one, whose live params are gathered
        and re-placed (the reshard pattern)."""
        from ...strategy.proto import load_strategy_file
        strategies = load_strategy_file(strategy_file)
        model.config.strategies.update(strategies)
        if not model._compiled:
            model.compile(mesh=mesh)
            model.init_layers(seed=model.config.seed)
        else:
            for op in model.layers:
                op.parallel_config = model.config.strategies.get(
                    op.name, op.parallel_config)
            if mesh is not None:
                model.mesh = mesh
            else:
                # the strategy names its own mesh (the same inference
                # compile() runs): rebuild when the live one differs
                from ...parallel.mesh import MachineMesh
                shape = model._infer_mesh_shape()
                if (model.mesh is None
                        or {a: s for a, s in model.mesh.sizes.items()
                            if s > 1} != {a: s for a, s in shape.items()
                                          if s > 1}):
                    model.mesh = MachineMesh(shape)
            # re-place live params under the strategy's shardings (the
            # partition-rule -> PartitionSpec pytree pattern); the AOT
            # forward cache lowered for the old placement must drop —
            # and so must any cached GraphDecoders, whose pool layout
            # was derived from the OLD mesh
            for p in model.parameters:
                if p.name in model._params:
                    val = model._gather_host(model._params[p.name])
                    model._params[p.name] = model._placed_param(p, val)
            model._fwd_compiled.clear()
            model._exec_digest_cache = None
            model.__dict__.pop("_gen_decoders", None)
            model._build_step_fns()
        return cls(model, **kwargs)


def _load_gen_faults() -> List[Dict]:
    """Materialize the FF_FAULT generation specs into per-engine firing
    state (start() calls this once per engine)."""
    out: List[Dict] = []
    for spec in faults.generation_faults():
        out.append({
            "kind": spec.kind,
            "n": int(spec.arg),
            "ms": float(spec.extras.get("ms", "50")),
            "fired": 0,
        })
    return out
