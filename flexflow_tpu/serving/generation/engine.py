"""GenerationEngine — iteration-level continuous batching over the
KV-cached decode path (docs/serving.md "Token generation").

The fixed-shape :class:`~flexflow_tpu.serving.engine.ServingEngine`
coalesces whole requests into one dispatch; token generation is a
different shape of problem — a request is a *stream* whose cost is
unknown up front (EOS may land anywhere).  Run-to-completion batching
wastes every slot whose stream finished early, so this engine schedules
at ITERATION granularity: a fixed ``slots``-wide decode batch shares
one preallocated KV cache, requests join a free slot at any step
boundary (one bucketed prefill dispatch seeds the slot and yields the
stream's first token — that's TTFT), every step runs ONE decode
dispatch + ONE token fetch for the whole batch (repo_lint RL010 bans
any other host sync in the loop), and a finished/cancelled stream frees
its slot for the next queued prompt immediately.

Admission reuses PR 8's machinery unchanged: the same
:class:`~flexflow_tpu.serving.batcher.MicroBatcher` (1 row per request)
provides the bounded queue with block/reject/shed_oldest policies,
per-request deadlines (a prompt still queued past its deadline expires
BEFORE any prefill is burned) and priority classes with the
anti-starvation aging bound — overload semantics carry over verbatim.

Strategy-sharded serving: :meth:`GenerationEngine.from_strategy` loads
a searched ``.pb``, re-places the params under the strategy's
PartitionSpecs (the SNIPPETS partition-rule → spec-pytree pattern) and
shards the KV cache heads over the ``c`` mesh axis / slots over ``n``
(analysis.kv_memory), so one checkpoint decodes tensor-parallel over
whatever mesh the strategy was searched for.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional

import jax
import numpy as np

from ... import faults
from ...compile_cache import enable as _enable_compile_cache
from ...fflogger import get_logger
from ...obs.flight import flight_dump, get_flight
from ...obs.trace import phase_of, tracer_from_config
from ...profiling import quantiles
from ..batcher import MicroBatcher, Request
from ..errors import GenerationCancelled, OverloadError, SheddedError
from ..metrics import ServingMetrics
from .decoder import GraphDecoder

_END = object()  # token-stream sentinel


def _resolve(fut: Future, out) -> bool:
    """Complete a stream future with a result or exception, from EITHER
    lifecycle state: pending (failure paths fire before the engine
    claimed it at prefill) or running (the decode loop claimed it).
    Unlike the serving engine's ``_resolve_future`` this must NOT call
    ``set_running_or_notify_cancel`` — on an already-claimed (RUNNING)
    future that raises and would silently swallow the resolution.
    Cancelled/finished futures return False (client interference is a
    drop, never a dispatcher-thread exception)."""
    try:
        if isinstance(out, BaseException):
            fut.set_exception(out)
        else:
            fut.set_result(out)
        return True
    except Exception:  # noqa: BLE001 — InvalidStateError & kin
        return False


class GenerationStream:
    """Client handle for one generation request: iterate it for tokens
    as they retire per decode step, or wait on :meth:`result` for the
    full sequence.

    ::

        stream = engine.submit([1, 2, 3], max_new_tokens=16)
        for tok in stream:          # yields as decode steps complete
            ...
        final = stream.result()     # np.int32 array of all new tokens

    ``cancel()`` is safe at any time: a queued request is dropped
    before any prefill; a mid-generation cancel frees its KV slot at
    the next step boundary and fails ONLY this stream with
    :class:`~flexflow_tpu.serving.errors.GenerationCancelled` — tokens
    already iterated remain valid."""

    def __init__(self, prompt_len: int, max_new: int, t_submit: float,
                 deadlined: bool = False, trace: Optional[str] = None):
        self.future: Future = Future()
        self.prompt_len = int(prompt_len)
        self.max_new = int(max_new)
        self.t_submit = t_submit
        self.deadlined = deadlined
        # sampled trace id (obs.trace) or None; the engine records this
        # stream's queue/prefill/terminal spans against it
        self.trace = trace
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._tokens: List[int] = []  # engine-thread writes, then frozen
        self._cancelled = threading.Event()
        # submit -> first token, set by the engine at prefill (None
        # until then) — per-stream SLO evidence for the goodput sweep
        self.ttft: Optional[float] = None

    # ---- client side ---------------------------------------------------
    def cancel(self) -> None:
        """Request cancellation.  Queued: the engine drops the request
        without a prefill (the future flips cancelled).  Generating:
        the slot frees at the next step boundary and the future fails
        with GenerationCancelled."""
        self._cancelled.set()
        # succeeds only while still queued (the engine claims the
        # future before prefill); a claimed future fails at the next
        # step boundary instead
        if self.future.cancel():
            self._q.put(_END)

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def tokens_so_far(self) -> List[int]:
        """Snapshot of the tokens retired so far (grows per step)."""
        return list(self._tokens)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full generated sequence (np.int32, length <= max_new) —
        blocks until EOS/max-tokens; raises the stream's failure."""
        return self.future.result(timeout)

    # ---- engine side ---------------------------------------------------
    def _emit(self, tok: int) -> None:
        self._tokens.append(tok)
        self._q.put(tok)

    def _finish(self) -> bool:
        done = _resolve(self.future, np.asarray(self._tokens, np.int32))
        self._q.put(_END)
        return done

    def _fail(self, exc: BaseException) -> bool:
        done = _resolve(self.future, exc)
        if done:
            self._q.put(exc)
        self._q.put(_END)
        return done


class _GenRequest(Request):
    """A queued prompt: a 1-row batcher Request carrying its stream.

    Deliberately NO ``stale=`` predicate: a cancelled-while-queued
    stream is already dropped at join time (the engine's
    ``set_running_or_notify_cancel`` claim fails on a cancelled
    future, so no prefill is burned), and a stale hook on EVERY
    request would flip the batcher's ``_watch`` fast path permanently
    on — every ``reap_expired()``/``poll()`` the decode loop runs
    would scan the whole queue under the lock even when nothing
    carries a deadline."""

    __slots__ = ("stream",)

    def __init__(self, stream: GenerationStream, prompt: np.ndarray,
                 on_done, t_submit: float, deadline=None, priority=0):
        super().__init__((prompt,), 1, on_done, t_submit,
                         deadline=deadline, priority=priority)
        self.stream = stream


class _Slot:
    """Dispatcher-thread-only state of one active decode slot."""

    __slots__ = ("stream", "last_token", "length", "generated")

    def __init__(self, stream: GenerationStream, first_token: int,
                 prompt_len: int):
        self.stream = stream
        self.last_token = first_token
        self.length = prompt_len  # positions materialized in the cache
        self.generated = 1        # prefill already yielded token #1


class GenerationMetrics(ServingMetrics):
    """ServingMetrics plus the generation gauges: windowed tokens/s,
    TTFT (submit -> first token, i.e. queue wait + prefill) and TPOT
    (decode-step wall time — the per-token latency every active stream
    pays) percentiles, token/prefill totals.  Emitted as ``gen_stats``
    events, the generation analogue of ``serve_stats``."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._ttfts: deque = deque(maxlen=4096)  # guarded_by: self._lock
        self._steps: deque = deque()             # guarded_by: self._lock
        # token/prefill lifetime totals live in the obs.registry like
        # every other serving counter — gen_stats events and /metrics
        # read the same children (docs/observability.md "Metrics")
        from ...obs.registry import get_registry
        reg = get_registry()
        kv = {"model": self.model_tag, "eng": self.eng_id}
        # into self._fams too: unregister() must reclaim these series
        # with the rest (the fleet's bounded-retirement scheme)
        self._fams["tokens"] = reg.counter(
            "ff_gen_tokens_total", "Tokens generated (incl. the "
            "prefill's first token)", ("model", "eng"))
        self._fams["prefills"] = reg.counter(
            "ff_gen_prefills_total", "Prefill dispatches (stream "
            "joins)", ("model", "eng"))
        self._ctr["tokens"] = self._fams["tokens"].labels(**kv)
        self._ctr["prefills"] = self._fams["prefills"].labels(**kv)

    @property
    def total_tokens(self) -> int:
        return int(self._ctr["tokens"].value)

    @property
    def total_prefills(self) -> int:
        return int(self._ctr["prefills"].value)

    def record_ttft(self, seconds: float) -> None:
        now = self.clock()
        self._ctr["prefills"].inc()
        with self._lock:
            self._ttfts.append((now, float(seconds)))

    def record_decode_step(self, ntokens: int, step_s: float) -> None:
        now = self.clock()
        self._ctr["tokens"].inc(int(ntokens))
        with self._lock:
            self._steps.append((now, int(ntokens), float(step_s)))
            horizon = now - self.window_s
            while self._steps and self._steps[0][0] < horizon:
                self._steps.popleft()

    def record_prefill_token(self) -> None:
        """The prefill's first token counts toward tokens/s too."""
        now = self.clock()
        self._ctr["tokens"].inc()
        with self._lock:
            self._steps.append((now, 1, 0.0))
            # trim here too: a max_new_tokens=1 workload never calls
            # record_decode_step, and the window must stay bounded
            horizon = now - self.window_s
            while self._steps and self._steps[0][0] < horizon:
                self._steps.popleft()

    def snapshot(self) -> Dict:
        snap = super().snapshot()
        now = self.clock()
        with self._lock:
            steps = list(self._steps)
            ttfts = [v for _, v in self._ttfts]
            total_tokens = self.total_tokens
            total_prefills = self.total_prefills
        span = self.window_s
        if steps:
            span = min(self.window_s, max(1e-6, now - steps[0][0]))
        toks = sum(s[1] for s in steps)
        tpots = [s[2] for s in steps if s[2] > 0]
        qt = quantiles(ttfts)
        qp = quantiles(tpots)

        def ms(v):
            return None if v != v else round(v * 1e3, 3)

        snap.update({
            "tokens_per_s": round(toks / span, 3),
            "tokens": total_tokens,
            "prefills": total_prefills,
            "ttft_p50_ms": ms(qt[0.5]), "ttft_p95_ms": ms(qt[0.95]),
            "ttft_p99_ms": ms(qt[0.99]),
            "tpot_p50_ms": ms(qp[0.5]), "tpot_p95_ms": ms(qp[0.95]),
            "tpot_p99_ms": ms(qp[0.99]),
        })
        return snap

    def emit(self, extra: Dict | None = None) -> None:
        get_logger("serve").event("gen_stats", **self.snapshot(),
                                  **(extra or {}))


class GenerationEngine:
    """Continuous-batching token generation over a compiled+initialized
    FFModel LM graph.

    ::

        engine = GenerationEngine(model, slots=8, eos_id=0)
        with engine:
            stream = engine.submit(prompt_ids, max_new_tokens=32)
            for tok in stream: ...
            out = stream.result()

    Knobs resolve from ``model.config`` (``--serve-gen-slots``,
    ``--serve-gen-max-seq``, ``--serve-gen-max-new``, and PR 8's
    ``--serve-max-queue-rows``/``--serve-admission``/
    ``--serve-starvation-ms`` for admission — the queue bound counts
    REQUESTS here, one row each) unless overridden.  ``clock``/``sleep``
    are injectable for deterministic fault tests (RL008)."""

    def __init__(self, model, slots: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 max_queue_requests: Optional[int] = None,
                 admission: Optional[str] = None,
                 starvation_ms: Optional[float] = None,
                 stats_every: int = 32, metrics_window_s: float = 30.0,
                 clock=time.monotonic, sleep=time.sleep,
                 name: str = ""):
        assert model._compiled, "compile() + init_layers() the model first"
        _enable_compile_cache()
        cfg = model.config
        if getattr(cfg, "serve_quantize", "") or \
                getattr(model, "_quantized", ""):
            # weight quantization is a DENSE-serving feature (the fleet
            # schema rejects it on generation tenants for the same
            # reason): silently serving full-precision weights while
            # the operator budgets HBM for int8 would overcommit the
            # KV+weight capacity plan
            raise ValueError(
                "serve_quantize is not supported by the generation "
                "engine (weight quantization covers dense serving "
                "only); unset FFConfig.serve_quantize for this model")
        self.model = model
        self.slots = int(slots or cfg.serve_gen_slots)
        seq_len = (model.input_tensors[0].shape[1]
                   if model.input_tensors else 0)
        self.max_seq = int(max_seq or cfg.serve_gen_max_seq or seq_len)
        self.max_new_tokens = int(max_new_tokens
                                  or cfg.serve_gen_max_new_tokens)
        self.eos_id = eos_id
        self.clock = clock
        self._sleep = sleep
        self.stats_every = int(stats_every)
        self.admission = (cfg.serve_admission if admission is None
                          else admission)
        self.max_queue_requests = int(
            cfg.serve_max_queue_rows if max_queue_requests is None
            else max_queue_requests)
        self._batcher = MicroBatcher(
            1, 0.0, clock=clock, max_queue_rows=self.max_queue_requests,
            admission=self.admission,
            starvation_ms=float(cfg.serve_starvation_ms
                                if starvation_ms is None
                                else starvation_ms))
        # tenant identity, stamped on gen_stats/gen_* events (fleet
        # co-residency: N engines in one process stay distinguishable;
        # FFConfig.serve_model_name is the single-engine default)
        self.name = str(name or cfg.serve_model_name)
        self.metrics = GenerationMetrics(
            window_s=metrics_window_s, clock=clock,
            queue_depth_fn=lambda: self._batcher.queue_depth,
            model=self.name)
        # observability plane: same contract as ServingEngine — one
        # lock-free `active` read per decode step when tracing is off,
        # flight taps installed for post-mortem dumps
        self._tracer = tracer_from_config(cfg)
        get_flight()
        self._decoder = GraphDecoder.for_model(model, self.slots,
                                               self.max_seq)
        # the ONE KV accounting (analysis.kv_memory): what lint's
        # FF108/FF121 gates charge for this deployment is what
        # init_cache() allocates
        from ...analysis.kv_memory import dtype_bytes, kv_cache_bytes
        self.kv_cache_bytes = kv_cache_bytes(
            model.layers,
            dict(model.mesh.sizes) if model.mesh is not None else None,
            self.slots, self.max_seq,
            kv_dtype_bytes=dtype_bytes(cfg.compute_dtype))
        # dispatcher-thread-only state (single writer, no lock)
        self._slots_state: List[Optional[_Slot]] = [None] * self.slots
        self._caches = None
        self._n_steps = 0
        self._gen_faults: List[Dict] = []
        # lifecycle (same single-use contract as ServingEngine)
        self._thread: Optional[  # guarded_by: self._lifecycle
            threading.Thread] = None
        self._stopped = False    # guarded_by: self._lifecycle
        self._draining = False   # guarded_by: self._lifecycle
        self._finalized = False  # guarded_by: self._lifecycle
        self._lifecycle = threading.Lock()
        self._closing = threading.Event()
        self._abort = threading.Event()
        self._shutdown_done = threading.Event()

    # ---- lifecycle -----------------------------------------------------
    def _warmup(self) -> None:
        """Compile every program the engine can dispatch BEFORE
        serving — the generation edition of ServingEngine's bucket
        warmup.  A prefill bucket compiled lazily mid-serving stalls
        the whole decode batch for the compile (measured ~0.6 s/bucket
        on CPU — every in-flight stream's TPOT eats it); paying all of
        it at start() keeps steady-state latency flat.  The dummy
        dispatches write into slot 0 / position 0 of the fresh cache,
        which the first real prefill overwrites."""
        params = self.model._params
        tok0 = np.zeros((1, 1), np.int32)
        for b in self._decoder.buckets:
            fn = self._decoder.prefill_fn(b)
            tokens = np.zeros((1, b), np.int32)
            tokens[0, :1] = tok0[0]
            first, self._caches = fn(params, self._caches, tokens,
                                     np.int32(0), np.int32(1))
        nxt, self._caches = self._decoder.decode_fn()(
            params, self._caches, np.zeros((self.slots,), np.int32),
            np.zeros((self.slots,), np.int32))
        jax.device_get(nxt)

    def start(self, warmup: bool = True) -> "GenerationEngine":
        with self._lifecycle:
            if self._stopped:
                raise RuntimeError(
                    "engine was stopped; create a new GenerationEngine "
                    "(decoders cache their compiled programs on the "
                    "model, so a fresh engine starts warm)")
            if self._thread is None:
                self._caches = self._decoder.init_cache()
                if warmup:
                    self._warmup()
                self._gen_faults = _load_gen_faults()
                get_logger("serve").event(
                    "gen_engine_start", model=self.name, slots=self.slots,
                    max_seq=self.max_seq,
                    kv_cache_bytes=self.kv_cache_bytes,
                    admission=self.admission,
                    max_queue_requests=self.max_queue_requests)
                self._thread = threading.Thread(
                    target=self._decode_loop, name="ff-generate",
                    daemon=True)
                self._thread.start()
        return self

    def stop(self) -> None:
        """Close admissions, serve everything queued and in flight to
        completion, stop the dispatcher, emit final stats.  Idempotent;
        single-use (see start()).  For a BOUNDED shutdown that sheds
        stragglers, see :meth:`drain`."""
        with self._lifecycle:
            self._closing.set()
            self._batcher.close()
            if self._thread is not None:
                self._thread.join()
                self._thread = None
                if not self._finalized:
                    self._finalized = True
                    self.metrics.emit(extra={"final": True,
                                             "slots": self.slots})
            else:
                now = self.clock()
                err = SheddedError(
                    "engine stopped before it was started")
                for r in self._batcher.fail_pending():
                    r.on_done(err, now)
            self._stopped = True
        # same registry retirement as ServingEngine.stop()
        self.metrics.release()
        self._shutdown_done.set()

    def drain(self, timeout: Optional[float] = None) -> Dict:
        """Bounded graceful shutdown: stop admitting, give in-flight
        generation ``timeout`` seconds, then shed the stragglers
        (queued prompts AND active streams fail with SheddedError).
        Returns the final stats snapshot; the engine is stopped
        afterwards."""
        with self._lifecycle:
            already = self._stopped or self._draining
            thread = self._thread
            if not already:
                self._draining = True
                self._closing.set()
                self._batcher.close()
        if already:
            self._shutdown_done.wait()
            return self.stats()
        get_logger("serve").event(
            "gen_drain", model=self.name, timeout_s=timeout,
            queue_depth=self._batcher.queue_depth)
        shed = 0
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                self._abort.set()
                now = self.clock()
                for r in self._batcher.fail_pending():
                    if r.on_done(SheddedError(
                            f"engine drained with work still queued "
                            f"(drain timeout {timeout}s)"), now):
                        shed += 1
                thread.join(timeout)
        else:
            now = self.clock()
            for r in self._batcher.fail_pending():
                if r.on_done(SheddedError(
                        "engine drained before it was started"), now):
                    shed += 1
        with self._lifecycle:
            self._stopped = True
            self._draining = False
            self._thread = None
            first = not self._finalized
            self._finalized = True
        snap = self.stats()
        if first:
            self.metrics.emit(extra={"final": True, "slots": self.slots,
                                     "drain_shed": shed})
        self.metrics.release()
        self._shutdown_done.set()
        return snap

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- fleet-managed (external) dispatch -----------------------------
    def begin_external_dispatch(self, warmup: bool = True
                                ) -> "GenerationEngine":
        """Fleet mode: ready the engine WITHOUT its own decode thread —
        a :class:`~flexflow_tpu.serving.fleet.FleetEngine` drives
        :meth:`dispatch_pending` decode steps from ONE shared
        dispatcher, interleaved with its co-resident tenants' dense
        dispatches under weighted-fair scheduling.  The producer side
        (submit, admission, deadlines) behaves exactly as under
        :meth:`start`."""
        with self._lifecycle:
            if self._stopped:
                raise RuntimeError(
                    "engine was stopped; create a new GenerationEngine")
            if self._thread is not None:
                raise RuntimeError(
                    "engine already runs its own decode thread")
            if self._caches is None:
                self._caches = self._decoder.init_cache()
                if warmup:
                    self._warmup()
                self._gen_faults = _load_gen_faults()
                get_logger("serve").event(
                    "gen_engine_start", model=self.name, slots=self.slots,
                    max_seq=self.max_seq,
                    kv_cache_bytes=self.kv_cache_bytes,
                    admission=self.admission,
                    max_queue_requests=self.max_queue_requests,
                    external=True)
        return self

    def dispatch_pending(self) -> Optional[float]:
        """Externally-driven decode step (fleet mode): expire queued
        deadlines, join queued prompts into free slots (prefill), and
        advance every active stream one token.  Returns the wall
        seconds spent — the device-time the fleet's fair scheduler
        charges this tenant — or None when nothing was due.  Error
        containment matches the owned decode loop (a poisoned step
        fails the active streams, the engine keeps serving)."""
        t0 = self.clock()
        self._batcher.reap_expired()
        self._admit()
        if not any(s is not None for s in self._slots_state):
            return None  # no active streams, nothing queued joined
        self._fire_slow_decode()
        try:
            self._decode_once()
        except BaseException as e:  # noqa: BLE001 — same containment
            # as _decode_loop: the step's failure is the streams', not
            # the fleet dispatcher's
            self._recover_from_dispatch_error(e, "gen_decode_error")
        return max(0.0, self.clock() - t0)

    @property
    def has_pending(self) -> bool:
        """Whether the engine has work an external dispatcher should
        schedule: active decode slots or queued prompts."""
        return (any(s is not None for s in self._slots_state)
                or self._batcher.queue_depth > 0)

    # ---- producer side -------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               priority: int = 0) -> GenerationStream:
        """Queue one prompt (1-D int token ids) and return its
        :class:`GenerationStream`.  Thread-safe.

        ``max_new_tokens`` caps the stream (default from config);
        generation also ends at ``eos_id`` when the engine has one.
        ``deadline_ms``/``priority`` behave exactly like the serving
        engine's (PR 8): a prompt still queued at its deadline expires
        with DeadlineExceeded before any prefill is burned; under a
        full bounded queue the admission policy applies per request."""
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if arr.size < 1:
            raise ValueError("empty prompt")
        # None-check, not truthiness: an explicit 0 must hit the guard
        # below, not silently fall back to the config default
        max_new = (self.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        if arr.size + max_new > self.max_seq:
            raise ValueError(
                f"prompt ({arr.size}) + max_new_tokens ({max_new}) "
                f"exceeds the KV cache length max_seq={self.max_seq}")
        t0 = self.clock()
        self.metrics.record_submitted()
        tr = self._tracer
        trace = tr.new_trace() if tr.active else None
        stream = GenerationStream(arr.size, max_new, t0,
                                  deadlined=deadline_ms is not None,
                                  trace=trace)
        deadline = None if deadline_ms is None else t0 + deadline_ms / 1e3
        metrics = self.metrics
        trace_term = self._trace_terminal

        def on_done(out, now: float) -> bool:
            # failure-path resolution only (expiry/shed/drain/stop);
            # the success path is the decode loop's _finish
            if isinstance(out, BaseException):
                if stream._fail(out):
                    metrics.record_failure(out)
                    trace_term(stream, phase_of(out), now)
                    return True
            return False

        req = _GenRequest(stream, arr.copy(), on_done, t0,
                          deadline=deadline, priority=priority)
        req.trace = trace

        def count_cancel(f):
            # a cancel-while-QUEUED succeeds on the pending future and
            # no resolution path ever runs for it (the join claim just
            # drops the request) — count the submitted stream's
            # outcome at the cancel instant, or the submitted ==
            # outcomes reconciliation leaks one per cancel.  A
            # mid-generation cancel cannot reach here with
            # cancelled()=True (cancel() on a RUNNING future fails;
            # _retire counts it via record_failure instead).
            if f.cancelled():
                metrics.record_cancelled()
                trace_term(stream, "cancelled", self.clock())

        stream.future.add_done_callback(count_cancel)
        try:
            self._batcher.submit(req)
        except OverloadError:
            self.metrics.record_rejected()
            self._trace_terminal(stream, "rejected", self.clock())
            raise
        except RuntimeError as e:
            self.metrics.record_rejected()
            self._trace_terminal(stream, "rejected", self.clock())
            raise OverloadError(
                f"engine is not admitting new work ({e})") from e
        return stream

    def _trace_terminal(self, stream: GenerationStream, phase: str,
                        now: float) -> None:
        """Record the stream's ONE terminal `request` span (no-op for
        unsampled streams) — phase counts reconcile with the metrics
        counters exactly like the dense engine's."""
        if stream.trace is None:
            return
        self._tracer.span(
            "request", stream.trace, stream.t_submit, now,
            tid=self.name or "generate", phase=phase,
            tokens=len(stream._tokens), model=self.name)

    def stats(self) -> Dict:
        active = sum(1 for s in self._slots_state if s is not None)
        return {**self.metrics.snapshot(), "slots": self.slots,
                "active_slots": active, "max_seq": self.max_seq,
                "kv_cache_bytes": self.kv_cache_bytes,
                "admission": self.admission,
                "max_queue_requests": self.max_queue_requests,
                "peak_queue_requests": self._batcher.peak_rows}

    # ---- dispatcher thread ---------------------------------------------
    def _decode_loop(self) -> None:
        """One iteration per decode step: admit queued prompts into
        free slots (prefill), then advance every active stream by one
        token with ONE dispatch + ONE fetch (RL010)."""
        while True:
            if self._abort.is_set():
                self._abort_active()
                return
            # expire queued deadlines at EVERY step boundary — with all
            # slots busy, _admit() never polls, and a deadline must
            # fail AT the deadline (PR 8's contract), not when a slot
            # happens to free
            self._batcher.reap_expired()
            self._admit()
            if not any(s is not None for s in self._slots_state):
                reqs = self._batcher.next_batch(timeout=0.05)
                if reqs:
                    for r in reqs:
                        self._join(r)
                    continue
                if (self._closing.is_set()
                        and self._batcher.queue_depth == 0):
                    return
                continue
            self._fire_slow_decode()
            try:
                self._decode_once()
            except BaseException as e:  # noqa: BLE001 — one poisoned
                # step must fail the ACTIVE streams, not kill the
                # dispatcher; queued prompts still get served
                self._recover_from_dispatch_error(e, "gen_decode_error")

    def _admit(self) -> None:
        """Join queued prompts into free slots at the step boundary —
        the continuous-batching join point."""
        for slot in range(self.slots):
            if self._slots_state[slot] is not None:
                continue
            batch = self._batcher.poll()
            if not batch:
                return
            for r in batch:
                self._join(r, slot)

    def _join(self, req: _GenRequest, slot: Optional[int] = None) -> None:
        if slot is None:
            slot = next((i for i, s in enumerate(self._slots_state)
                         if s is None), None)
            if slot is None:
                # unreachable from the loop (joins only happen with a
                # free slot), but never strand a stream if it ever is
                req.stream._fail(SheddedError(
                    "internal: no free decode slot at join"))
                return
        stream = req.stream
        try:
            claimed = stream.future.set_running_or_notify_cancel()
        except RuntimeError:
            claimed = False
        if not claimed:
            return  # cancelled/expired while queued (the cancel was
            #         counted at cancel() time — see submit())
        prompt = req.xs[0]
        traced = self._tracer.active
        t_join = self.clock() if traced else 0.0
        try:
            bucket = self._decoder.prefill_bucket(prompt.size)
            tokens = np.zeros((1, bucket), np.int32)
            tokens[0, :prompt.size] = prompt
            fn = self._decoder.prefill_fn(bucket)
            with jax.profiler.StepTraceAnnotation(
                    "gen-prefill", step_num=self._n_steps):
                first, self._caches = fn(
                    self.model._params, self._caches, tokens,
                    np.int32(slot), np.int32(prompt.size))
                # one fetch per JOIN (not per step): the stream's first
                # token comes out of the prefill dispatch itself
                tok = int(jax.device_get(first))
        except BaseException as e:  # noqa: BLE001 — a poisoned prefill
            # fails the joining stream AND (because the dispatch may
            # have consumed the donated cache pytree) every in-flight
            # stream; the engine re-arms and keeps serving the queue
            if stream._fail(e):
                self.metrics.record_failure(e)
                self._trace_terminal(stream, "error", self.clock())
            self._recover_from_dispatch_error(e, "gen_prefill_error")
            return
        now = self.clock()
        st = _Slot(stream, tok, prompt.size)
        self._slots_state[slot] = st
        stream.ttft = now - stream.t_submit
        stream._emit(tok)
        self.metrics.record_ttft(stream.ttft)
        self.metrics.record_prefill_token()
        if traced and stream.trace is not None:
            tname = self.name or "generate"
            self._tracer.span("queue", stream.trace, stream.t_submit,
                              t_join, tid=tname, slot=slot)
            self._tracer.span("prefill", stream.trace, t_join, now,
                              tid=tname, slot=slot, bucket=bucket,
                              prompt_len=int(prompt.size))
        self._retire(slot, st, now)

    def _decode_once(self) -> None:
        """Advance the whole decode batch one position: one dispatch,
        one token fetch, scatter to streams."""
        tokens = np.zeros((self.slots,), np.int32)
        pos = np.zeros((self.slots,), np.int32)
        nactive = 0
        for i, s in enumerate(self._slots_state):
            if s is not None:
                tokens[i] = s.last_token
                pos[i] = s.length
                nactive += 1
        fn = self._decoder.decode_fn()
        # ONE lock-free tracing check per decode step (hot-path
        # contract, docs/observability.md)
        traced = self._tracer.active
        t0 = self.clock()
        with jax.profiler.StepTraceAnnotation("generate",
                                              step_num=self._n_steps):
            nxt, self._caches = fn(self.model._params, self._caches,
                                   tokens, pos)
            # THE one host sync per decode step for the whole batch —
            # per-stream tokens are scattered from it below (RL010)
            host = np.asarray(jax.device_get(nxt))
        now = self.clock()
        self._n_steps += 1
        for i, s in enumerate(self._slots_state):
            if s is None:
                continue
            tok = int(host[i])
            s.length += 1
            s.generated += 1
            s.last_token = tok
            s.stream._emit(tok)
            self._retire(i, s, now)
        if traced:
            self._tracer.span("decode_step", None, t0, now,
                              tid=self.name or "generate",
                              step=self._n_steps - 1, active=nactive)
        self.metrics.record_decode_step(nactive, now - t0)
        self._fire_cancel_at_token(now)
        if self.stats_every and self._n_steps % self.stats_every == 0:
            self.metrics.emit(extra={"slots": self.slots,
                                     "active": nactive,
                                     "kv_cache_bytes":
                                         self.kv_cache_bytes})

    def _recover_from_dispatch_error(self, e: BaseException,
                                     event: str) -> None:
        """A failed prefill/decode dispatch raised AFTER the cache
        pytree was donated: off-CPU the buffers are invalidated, so
        every active stream's state is unrecoverable — fail them all,
        reallocate the cache, and keep serving queued prompts (the
        engine recovers; a poisoned dispatch must never wedge it on
        'Array has been deleted' forever)."""
        failed = 0
        now = self.clock()
        for i, s in enumerate(self._slots_state):
            if s is None:
                continue
            if s.stream._fail(e):
                self.metrics.record_failure(e)
                self._trace_terminal(s.stream, "error", now)
                failed += 1
            self._slots_state[i] = None
        self._caches = self._decoder.init_cache()
        get_logger("serve").event(  # RL011-ok: gen_decode_error |
            # gen_prefill_error, both declared in obs/events.py —
            # callers pass the literal
            event, model=self.name, step=self._n_steps,
            error=f"{type(e).__name__}: {e}"[:300],
            failed_streams=failed)
        # generation's dispatch-error flight trigger (no-op unless
        # FF_FLIGHT_DIR is set)
        flight_dump(event, extra={"model": self.name,
                                  "step": self._n_steps,
                                  "error": f"{type(e).__name__}: {e}"[:300],
                                  "failed_streams": failed})

    def _retire(self, slot: int, s: _Slot, now: float) -> None:
        """Free the slot if its stream finished or was cancelled —
        run at every step boundary, so a mid-generation cancel frees
        KV capacity for the next queued prompt immediately."""
        if s.stream.cancelled:
            exc = GenerationCancelled(
                f"stream cancelled after {s.generated} token(s); "
                f"KV slot {slot} freed")
            if s.stream._fail(exc):
                self.metrics.record_failure(exc)
                self._trace_terminal(s.stream, "cancelled", now)
            self._slots_state[slot] = None
            return
        done = s.generated >= s.stream.max_new or (
            self.eos_id is not None and s.last_token == self.eos_id)
        if done:
            if s.stream._finish():
                self.metrics.record_request(now - s.stream.t_submit,
                                            deadlined=s.stream.deadlined)
                self._trace_terminal(s.stream, "completed", now)
            self._slots_state[slot] = None

    def _abort_active(self) -> None:
        """drain(timeout) expired: shed whatever is still decoding."""
        now = self.clock()
        for i, s in enumerate(self._slots_state):
            if s is None:
                continue
            exc = SheddedError(
                "engine drained mid-generation (drain timeout)")
            if s.stream._fail(exc):
                self.metrics.record_failure(exc)
                self._trace_terminal(s.stream, "shed", now)
            self._slots_state[i] = None

    # ---- fault injection (FF_FAULT generation kinds) -------------------
    def _fire_slow_decode(self) -> None:
        for st in self._gen_faults:
            if st["kind"] == "serve_slow_decode" and st["fired"] < st["n"]:
                st["fired"] += 1
                self._sleep(st["ms"] / 1e3)

    def _fire_cancel_at_token(self, now: float) -> None:
        for st in self._gen_faults:
            if st["kind"] != "serve_cancel_at_token" or st["fired"]:
                continue
            for i, s in enumerate(self._slots_state):
                if s is not None and s.generated >= st["n"]:
                    st["fired"] = 1
                    get_logger("serve").event(
                        "gen_fault_cancel", model=self.name, slot=i,
                        generated=s.generated, at_token=st["n"])
                    s.stream.cancel()
                    self._retire(i, s, now)
                    break

    # ---- strategy-sharded construction ---------------------------------
    @classmethod
    def from_strategy(cls, model, strategy_file: str, mesh=None,
                      **kwargs) -> "GenerationEngine":
        """Build a tensor-parallel generation engine from a searched
        strategy ``.pb``: load the per-op ParallelConfigs, compile the
        model against them (ffcheck-verified, mesh inferred from the
        strategy when not given), place/re-place every parameter under
        its strategy PartitionSpec, and shard the KV cache heads over
        the ``c`` axis — one checkpoint, any searched sharding.

        Accepts a fresh (uncompiled) model — compiled+initialized here
        — or an already-initialized one, whose live params are gathered
        and re-placed (the reshard pattern)."""
        from ...strategy.proto import load_strategy_file
        strategies = load_strategy_file(strategy_file)
        model.config.strategies.update(strategies)
        if not model._compiled:
            model.compile(mesh=mesh)
            model.init_layers(seed=model.config.seed)
        else:
            for op in model.layers:
                op.parallel_config = model.config.strategies.get(
                    op.name, op.parallel_config)
            if mesh is not None:
                model.mesh = mesh
            else:
                # the strategy names its own mesh (the same inference
                # compile() runs): rebuild when the live one differs
                from ...parallel.mesh import MachineMesh
                shape = model._infer_mesh_shape()
                if (model.mesh is None
                        or {a: s for a, s in model.mesh.sizes.items()
                            if s > 1} != {a: s for a, s in shape.items()
                                          if s > 1}):
                    model.mesh = MachineMesh(shape)
            # re-place live params under the strategy's shardings (the
            # partition-rule -> PartitionSpec pytree pattern); the AOT
            # forward cache lowered for the old placement must drop —
            # and so must any cached GraphDecoders, whose KV-cache
            # layout was derived from the OLD mesh
            for p in model.parameters:
                if p.name in model._params:
                    val = model._gather_host(model._params[p.name])
                    model._params[p.name] = model._placed_param(p, val)
            model._fwd_compiled.clear()
            model._exec_digest_cache = None
            model.__dict__.pop("_gen_decoders", None)
            model._build_step_fns()
        return cls(model, **kwargs)


def _load_gen_faults() -> List[Dict]:
    """Materialize the FF_FAULT generation specs into per-engine firing
    state (start() calls this once per engine)."""
    out: List[Dict] = []
    for spec in faults.generation_faults():
        out.append({
            "kind": spec.kind,
            "n": int(spec.arg),
            "ms": float(spec.extras.get("ms", "50")),
            "fired": 0,
        })
    return out
