"""KV page pool + shared-prefix trie — the host-side memory manager of
the paged generation engine (docs/serving.md "Paged KV & prefix
caching").

Everything here is dispatcher-thread-only pure Python: the pool hands
out page ids, refcounts them, tracks the in-use high-water mark, and
evicts cached prefix pages under pressure; the trie maps token-id
chains (one node per FULL page of tokens) to pooled pages so a submit
whose prompt extends a cached prefix skips recomputing the shared
pages.  The device side only ever sees page ids as gather/scatter
indices (ops/attention.py ``prefill_paged``/``decode_paged``).

Sharing is all-or-nothing per page, and a shared page is immutable by
construction: a lookup only ever matches COMPLETE pages strictly
covered by the prompt's first ``len - 1`` positions, so the prefill
recomputes at least the last prompt position and every write (suffix
prefill rows, decode tokens) lands in the slot's PRIVATE pages — the
copy-on-write case where a stream would mutate shared history cannot
arise, divergence simply stops the trie walk and allocates private
pages from there.

This module is ALSO the one place pool device arrays are allocated
(:func:`alloc_pool_arrays`) — repo_lint RL013 bans KV-shaped
``jnp.zeros``/``np.zeros`` anywhere else under ``serving/generation/``
so no second allocation path can drift from the
``analysis.kv_memory`` accounting.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...analysis.kv_memory import DEFAULT_PAGE_SIZE


class KVPagePool:
    """Fixed-size pool of interchangeable KV pages (one id spans every
    attention op's K/V pools — allocation is in lockstep across ops).
    Single-threaded by design: only the engine's dispatcher thread
    allocates/frees (the same single-writer discipline as the slot
    table)."""

    def __init__(self, num_pages: int, page_size: int = DEFAULT_PAGE_SIZE):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size) or DEFAULT_PAGE_SIZE
        # the OOB sentinel: gather clamps it (masked anyway), scatter
        # mode='drop' discards writes to it — "no page" on device
        self.no_page = self.num_pages
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._refs: Dict[int, int] = {}
        self.high_water = 0
        self.allocs = 0

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def pages_free(self) -> int:
        return len(self._free)

    def alloc(self) -> Optional[int]:
        """One fresh page at refcount 1, or None when exhausted (the
        caller evicts from the prefix cache and retries, then fails the
        stream — never blocks: this runs on the dispatcher thread)."""
        if not self._free:
            return None
        page = self._free.pop()
        self._refs[page] = 1
        self.allocs += 1
        self.high_water = max(self.high_water, self.pages_in_use)
        return page

    def ref(self, page: int) -> None:
        self._refs[page] += 1

    def release(self, page: int) -> bool:
        """Drop one reference; True when the page returned to the free
        list (refcount hit zero)."""
        n = self._refs[page] - 1
        if n > 0:
            self._refs[page] = n
            return False
        del self._refs[page]
        self._free.append(page)
        return True

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)


class _TrieNode:
    __slots__ = ("page", "children", "parent", "key", "last_used")

    def __init__(self, page: int, parent: Optional["_TrieNode"],
                 key: Tuple[int, ...]):
        self.page = page
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.parent = parent
        self.key = key
        self.last_used = 0


class PrefixCache:
    """Ref-counted prefix trie over FULL pages of prompt token ids.

    One node per page: the path root -> node spells the token prefix
    the node's page holds the K/V for.  Children are keyed on the exact
    page token tuple (a hash chain with exact-match confirmation — two
    different prefixes can never alias, so a hit is always
    bit-identical history).  The trie holds ONE pool reference per
    node; lookups take an extra reference per matched page for the
    joining slot.  Eviction is LRU over leaf nodes nobody else
    references — interior nodes and pages still held by live slots are
    never evicted."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._root: Dict[Tuple[int, ...], _TrieNode] = {}
        self._nodes = 0
        self._clock = 0  # LRU tick (monotonic counter, no wall time)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return self._nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _pages_of(tokens, page_size: int) -> List[Tuple[int, ...]]:
        """Complete-page token tuples strictly covering positions
        [0, len-1): the last prompt position is always recomputed (it
        yields the stream's first token), so the page holding it is
        only shareable once COMPLETE — see the immutability note in
        the module docstring."""
        n = len(tokens)
        full = max(0, (n - 1)) // page_size
        return [tuple(int(t) for t in tokens[i * page_size:
                                             (i + 1) * page_size])
                for i in range(full)]

    def lookup(self, tokens) -> List[int]:
        """Walk the trie along the prompt's full pages; returns the
        matched page ids IN ORDER with one pool reference taken per
        page for the caller (the joining slot).  The caller's prefill
        starts at ``len(result) * page_size``."""
        out: List[int] = []
        level = self._root
        now = self._tick()
        for key in self._pages_of(tokens, self.page_size):
            node = level.get(key)
            if node is None:
                break
            node.last_used = now
            self.pool.ref(node.page)
            out.append(node.page)
            level = node.children
        if out:
            self.hits += 1
        else:
            self.misses += 1
        return out

    def insert(self, tokens, pages: List[int]) -> int:
        """Promote a slot's freshly-computed full-page prefix into the
        trie: ``pages[i]`` holds the K/V of the prompt's i-th full
        page.  Pages already cached (the slot's own lookup hits) are
        skipped; new nodes take one extra pool reference (the trie's).
        Returns the number of nodes added."""
        added = 0
        level = self._root
        parent: Optional[_TrieNode] = None
        now = self._tick()
        keys = self._pages_of(tokens, self.page_size)
        for key, page in zip(keys, pages):
            node = level.get(key)
            if node is None:
                node = _TrieNode(page, parent, key)
                node.last_used = now
                self.pool.ref(page)
                level[key] = node
                self._nodes += 1
                added += 1
            else:
                node.last_used = now
            parent = node
            level = node.children
        return added

    def _evictable(self) -> List[_TrieNode]:
        out: List[_TrieNode] = []
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.pool.refcount(node.page) == 1:
                # a leaf only the trie references: safe to drop
                out.append(node)
        return out

    def _evict_node(self, node: _TrieNode) -> None:
        level = (node.parent.children if node.parent is not None
                 else self._root)
        del level[node.key]
        self._nodes -= 1
        self.pool.release(node.page)
        self.evictions += 1

    def evict(self, count: int) -> int:
        """Free up to ``count`` least-recently-used unreferenced LEAF
        pages back to the pool (page-pool pressure).  ONE evictability
        walk covers a whole batch — evicting a leaf can only ever
        EXPOSE its parent as a new leaf, never invalidate another
        collected victim, so the sorted victim list stays valid while
        it drains; only when it runs dry mid-batch (freed leaves'
        parents now evictable) does another walk happen.  Returns the
        number of pages freed — 0 means every cached page backs a
        live slot."""
        freed = 0
        while freed < count:
            victims = sorted(self._evictable(),
                             key=lambda n: n.last_used)
            if not victims:
                break
            for node in victims:
                if freed >= count:
                    break
                self._evict_node(node)
                freed += 1
        return freed

    def evict_one(self) -> bool:
        """Single-page :meth:`evict` (the unit-test surface)."""
        return self.evict(1) == 1

    def clear(self) -> None:
        """Release every cached page (engine shutdown)."""
        stack = list(self._root.values())
        self._root = {}
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.pool.release(node.page)
        self._nodes = 0


def alloc_pool_arrays(layout: Dict[str, Dict], mesh, compute_dtype):
    """Materialize the ``analysis.kv_memory.kv_cache_layout`` on
    device: attention K/V page pools and LSTM state pairs, placed under
    the layout's PartitionSpec entries.  THE one KV allocation site
    (repo_lint RL013) — byte-for-byte what :func:`kv_page_plan`
    accounts, pinned in tests/test_generation.py."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    compute_dt = jnp.dtype(compute_dtype)
    caches: Dict[str, Dict[str, jax.Array]] = {}
    for name, ent in layout.items():
        dt = compute_dt if ent["dtype"] == "compute" else jnp.float32
        sub: Dict[str, jax.Array] = {}
        for leaf, shape in ent["shapes"].items():
            arr = jnp.zeros(shape, dt)
            if mesh is not None and mesh.is_distributed:
                arr = jax.device_put(
                    arr, mesh.sharding(PartitionSpec(
                        *ent["entries"][leaf])))
            sub[leaf] = arr
        caches[name] = sub
    return caches


def export_pages(caches, pages: List[int], num_pages: int,
                 pad_to: int = 0):
    """Gather a slot's page rows out of every pool leaf and bring them
    to host in ONE ``device_get`` — the export half of disaggregated
    prefill/decode migration (docs/serving.md "Disaggregated
    prefill/decode").  ``pages`` is the slot's page-id chain IN ORDER;
    every leaf must be page-major (``shape[0] == num_pages``), which is
    true exactly for the attention K/V pools — LSTM ``state`` leaves
    are slot-major and cannot migrate (the engine gates migration on
    chunkable attention graphs for the same reason).  Returns a host
    pytree ``{op: {leaf: np.ndarray[rows, ...]}}``.

    ``pad_to`` pads the gather index to a FIXED row count by repeating
    the last page id (the caller passes its pages-per-slot maximum):
    the gather then traces one XLA program per pool geometry instead
    of one per chain length, so a migration never pays a fresh compile
    mid-serve.  :func:`import_pages` mirrors the padding; the real
    chain length travels beside the payload."""
    import jax
    import numpy as np

    idx = np.asarray(list(pages), np.int32)
    if pad_to > idx.size:
        idx = np.concatenate(
            [idx, np.full(pad_to - idx.size, idx[-1], np.int32)])
    gathered: Dict[str, Dict] = {}
    for name, sub in caches.items():
        rows = {}
        for leaf, arr in sub.items():
            if arr.shape[0] != num_pages:
                raise ValueError(
                    f"cache leaf {name}.{leaf} is not page-major "
                    f"(shape {tuple(arr.shape)}, pool has {num_pages} "
                    f"pages): this graph's state cannot migrate")
            rows[leaf] = arr[idx]
        gathered[name] = rows
    # one transfer for the whole pytree (RL010-class budget: migration
    # costs one sync on the source, one put on the destination)
    return jax.device_get(gathered)


def import_pages(caches, payload, pages: List[int]):
    """Scatter an :func:`export_pages` payload into ``pages`` of the
    DESTINATION pool with ONE ``device_put`` of the payload pytree —
    the import half of KV page migration.  ``pages`` are freshly
    allocated destination page ids (one per exported page, same order).
    Returns the updated caches pytree (functional ``.at[].set`` — the
    caller reassigns its ``_caches``).

    A payload with MORE rows than ``pages`` was export-padded: the
    destination index is padded the same way (repeat the last real
    page id), so the duplicate scatter positions rewrite the last real
    page with its own row — idempotent — and the scatter keeps one
    fixed shape per pool geometry.

    The pool leaf is DONATED into the scatter: the caller must treat
    the input caches as consumed (the engine reassigns ``_caches`` to
    the return value, and nothing else aliases the pool arrays), so
    the update is in-place where the backend allows instead of a
    full-pool copy per migration."""
    import jax
    import numpy as np

    idx = np.asarray(list(pages), np.int32)
    dev = jax.device_put(payload)
    rows0 = next(iter(next(iter(dev.values())).values())).shape[0] \
        if isinstance(dev, dict) and dev else idx.size
    if rows0 > idx.size:
        idx = np.concatenate(
            [idx, np.full(rows0 - idx.size, idx[-1], np.int32)])
    # validate EVERYTHING before the first donating scatter: a graph/
    # geometry mismatch must leave the resident pool untouched (the
    # engine's per-stream containment); once validation passed, the
    # only scatter failures left are catastrophic backend errors
    for name, sub in caches.items():
        rows = dev.get(name) if isinstance(dev, dict) else None
        if rows is None or set(rows) != set(sub):
            raise ValueError(
                f"migration payload does not cover cache op {name!r}: "
                f"source and destination graphs differ")
        for leaf, arr in sub.items():
            val = rows[leaf]
            if tuple(val.shape[1:]) != tuple(arr.shape[1:]) \
                    or val.shape[0] != idx.size:
                raise ValueError(
                    f"migration payload {name}.{leaf} shape "
                    f"{tuple(val.shape)} does not fit destination pool "
                    f"leaf {tuple(arr.shape)} over {idx.size} page(s): "
                    f"page geometry must match across engines")
    out: Dict[str, Dict] = {}
    for name, sub in caches.items():
        rows = dev[name]
        out[name] = {
            leaf: _scatter_rows(arr, idx, rows[leaf].astype(arr.dtype))
            for leaf, arr in sub.items()}
    return out


_SCATTER_ROWS = None


def _scatter_rows(arr, idx, val):
    """One jitted, BUFFER-DONATING row scatter shared by every import
    (fixed shape per pool geometry — see the padding contract above):
    in-place on backends that honor donation, one compile ever."""
    global _SCATTER_ROWS
    if _SCATTER_ROWS is None:
        import jax
        _SCATTER_ROWS = jax.jit(
            lambda a, i, v: a.at[i].set(v), donate_argnums=(0,))
    return _SCATTER_ROWS(arr, idx, val)


__all__ = ["KVPagePool", "PrefixCache", "alloc_pool_arrays",
           "export_pages", "import_pages"]
