"""Sampling strategies + speculative rejection acceptance (docs/
serving.md "Speculative decoding & sampling").

Everything here is pure jnp and runs INSIDE the jitted decode/draft/
verify programs — no host RNG, no wall-clock entropy (RL014: serving
randomness must derive from the per-request seed the caller threads
through).  Keys are raw ``jax.random.PRNGKey(seed)`` keys folded with
the GLOBAL token position plus a small stream tag, so

* the same ``(seed, request)`` replays the same tokens run over run
  (the determinism pin in tests/test_generation.py), and
* the draft proposal, the accept/reject uniform and the residual
  resample at one position are three INDEPENDENT streams — the
  independence the rejection-sampling exactness argument needs (the
  uniform must not be correlated with the proposal it judges).

The acceptance rule is Leviathan-style speculative sampling: accept
draft token ``x ~ q`` with probability ``min(1, p(x)/q(x))``; on the
first rejection resample from the residual ``norm(max(p - q, 0))``.
The marginal of the emitted token is exactly ``p`` — pinned by the
seeded property test against the direct target sampler.  With one-hot
(greedy) distributions the rule degenerates to an argmax equality
check with an argmax correction, which is why greedy speculation can
share this machinery at all.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # same finite mask value as ops.attention

# fold_in stream tags: one sub-stream per distinct random decision at a
# given (seed, position) so they are mutually independent
STREAM_MAIN = 0      # plain (non-speculative) sampled decode
STREAM_DRAFT = 1     # draft proposal at a position
STREAM_ACCEPT = 2    # accept/reject uniform at a position
STREAM_RESIDUAL = 3  # residual resample at the first rejected position


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling strategy.  ``temperature <= 0`` is greedy
    argmax (the default — and the engine keeps all-greedy batches on
    the unsampled decode program so the bit-parity pins hold exactly);
    ``top_k <= 0`` keeps the whole vocabulary; ``top_p`` is the nucleus
    mass (1.0 = no nucleus cut).  ``seed`` is the request's PRNG root:
    sampling is deterministic per (seed, request)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, "
                             f"got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def is_greedy(self) -> bool:
        return self.temperature <= 0.0


GREEDY = SamplingParams()


# ---- key plumbing (inside jitted programs) -----------------------------
def request_keys(seeds):
    """(n,) int32 per-slot seeds -> (n, 2) raw PRNG keys."""
    return jax.vmap(jax.random.PRNGKey)(seeds)


def position_keys(keys, pos, stream: int):
    """Fold the GLOBAL token position plus a stream tag into each
    slot's root key: ``keys`` (n, 2), ``pos`` (n,) int32 -> (n, 2)."""
    k = jax.vmap(jax.random.fold_in)(keys, pos)
    return jax.vmap(jax.random.fold_in, in_axes=(0, None))(k, stream)


def uniform_01(keys):
    """(..., 2) keys -> (...,) independent U[0,1) floats."""
    shape = keys.shape[:-1]
    flat = keys.reshape(-1, 2)
    u = jax.vmap(lambda k: jax.random.uniform(k))(flat)
    return u.reshape(shape)


def probs_to_logits(p):
    """Normalized probs -> logits with exact ``NEG_INF`` at zero mass
    (so ``jax.random.categorical`` can never emit a filtered token)."""
    return jnp.where(p > 0.0, jnp.log(jnp.maximum(p, 1e-38)), NEG_INF)


def categorical(keys, probs):
    """(..., 2) keys + (..., V) probs -> (...,) int32 draws.  One-hot
    rows return their argmax deterministically (the Gumbel perturbation
    is finite; ``NEG_INF`` mass can never win)."""
    lead = probs.shape[:-1]
    v = probs.shape[-1]
    flat_k = keys.reshape(-1, 2)
    flat_p = probs.reshape(-1, v)
    t = jax.vmap(jax.random.categorical)(flat_k, probs_to_logits(flat_p))
    return t.reshape(lead).astype(jnp.int32)


# ---- strategy: logits -> filtered target distribution ------------------
def filtered_probs(logits, temperature, top_k, top_p):
    """Apply per-row temperature / top-k / top-p and normalize:
    ``logits`` (n, V) + (n,) strategy arrays -> (n, V) probs.

    Rows with ``temperature <= 0`` come back as the EXACT one-hot of
    ``argmax(logits)`` — the same argmax the unsampled decode program
    takes, so a greedy request routed through the sampled program still
    emits the greedy token (ties break identically: same op, same
    input).  Ties AT the top-p cut value stay in (the standard
    keep-at-least-the-nucleus convention)."""
    logits = logits.astype(jnp.float32)
    n, v = logits.shape
    greedy = temperature <= 0.0
    t = jnp.where(greedy, 1.0, temperature)
    scaled = logits / t[:, None]
    # top-k: keep the k largest (k <= 0 keeps all)
    k = jnp.clip(jnp.where(top_k <= 0, v, top_k), 1, v)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(desc, (k - 1)[:, None], axis=-1)
    scaled = jnp.where(scaled < kth, NEG_INF, scaled)
    # top-p nucleus over the k-survivors: keep the smallest prefix of
    # the sorted probs whose mass reaches top_p (top-1 always kept)
    probs = jax.nn.softmax(scaled, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    csum = jnp.cumsum(sp, axis=-1)
    keep = (csum - sp) < top_p[:, None]
    cut = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1)
    scaled = jnp.where(probs < cut[:, None], NEG_INF, scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    onehot = jax.nn.one_hot(jnp.argmax(logits, axis=-1), v,
                            dtype=jnp.float32)
    return jnp.where(greedy[:, None], onehot, probs)


# ---- speculative acceptance --------------------------------------------
def residual_probs(p, q):
    """The rejection residual ``norm(max(p - q, 0))`` per row; rows
    where p == q (zero residual) fall back to ``p`` itself — any token
    there was accepted with probability 1, so the branch only guards
    numerics, never changes the marginal."""
    r = jnp.maximum(p - q, 0.0)
    rs = jnp.sum(r, axis=-1, keepdims=True)
    return jnp.where(rs > 0.0, r / jnp.where(rs > 0.0, rs, 1.0), p)


def speculative_accept(d, p, q, accept_keys, residual_keys):
    """Vectorized rejection-sampling acceptance over a verify window.

    ``d`` (n, W) draft proposals; ``p``/``q`` (n, W, V) target/draft
    probs at the SAME positions; ``accept_keys``/``residual_keys``
    (n, W, 2) per-position key streams.  Returns ``(n_accept (n,),
    out (n, W))``: ``out[:, :n]`` are the accepted draft tokens and
    ``out[:, n]`` (when n < W) is the residual resample — exactly the
    tokens the stream emits, in order.  The marginal of each emitted
    token is the target distribution ``p`` (seeded property test in
    tests/test_generation.py)."""
    n, w, _ = p.shape
    pd = jnp.take_along_axis(p, d[..., None], axis=-1)[..., 0]
    qd = jnp.take_along_axis(q, d[..., None], axis=-1)[..., 0]
    u = uniform_01(accept_keys)                              # (n, W)
    # accept with prob min(1, p/q): u*q <= p avoids the 0/0 division
    accept = u * qd <= pd
    cum = jnp.cumprod(accept.astype(jnp.int32), axis=-1)
    n_acc = jnp.sum(cum, axis=-1)                            # (n,)
    # residual resample at the FIRST rejected position (index clipped
    # for full-accept rows, whose resample is computed then discarded)
    idx = jnp.minimum(n_acc, w - 1)
    p_r = jnp.take_along_axis(p, idx[:, None, None], axis=1)[:, 0]
    q_r = jnp.take_along_axis(q, idx[:, None, None], axis=1)[:, 0]
    keys_r = jnp.take_along_axis(residual_keys, idx[:, None, None],
                                 axis=1)[:, 0]               # (n, 2)
    c = categorical(keys_r, residual_probs(p_r, q_r))        # (n,)
    out = jnp.where(jnp.arange(w)[None, :] == n_acc[:, None],
                    c[:, None], d)
    return n_acc.astype(jnp.int32), out.astype(jnp.int32)


def speculative_sample(key, p, q, n: int):
    """Reference single-position speculative sampler for the property
    test: draw ``n`` independent tokens through the draft -> accept ->
    residual path with target probs ``p`` (V,) and draft probs ``q``
    (V,).  The returned empirical distribution must match ``p`` — the
    per-position invariant the windowed :func:`speculative_accept`
    inherits by induction."""
    kd, ku, kr = jax.random.split(key, 3)
    d = jax.random.categorical(kd, probs_to_logits(q), shape=(n,))
    u = jax.random.uniform(ku, (n,))
    accept = u * q[d] <= p[d]
    r = residual_probs(p[None], q[None])[0]
    c = jax.random.categorical(kr, probs_to_logits(r), shape=(n,))
    return jnp.where(accept, d, c).astype(jnp.int32)
