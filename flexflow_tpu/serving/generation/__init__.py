"""flexflow_tpu.serving.generation — the token-generation subsystem
(docs/serving.md "Token generation"): KV-cached autoregressive decode
over an FFModel graph, an iteration-level continuous-batching
:class:`GenerationEngine` with streaming outputs, and strategy-sharded
serving (``GenerationEngine.from_strategy`` turns a searched ``.pb``
into PartitionSpecs for params AND the KV cache and decodes
tensor-parallel over the mesh)."""

from .decoder import GraphDecoder
from .engine import GenerationEngine, GenerationMetrics, GenerationStream
from .sampling import SamplingParams

__all__ = ["GenerationEngine", "GenerationStream", "GenerationMetrics",
           "GraphDecoder", "SamplingParams"]
