"""GraphDecoder — autoregressive execution of an FFModel graph over a
PAGED KV cache.

The training/serving executor runs the graph at full sequence length;
generation needs the same graph one position at a time against state
that scales with *live tokens*, not ``slots x max_seq``.  This module
derives both halves from the layer list itself:

* **prefill chunk** — the forward over a ``(1, bucket)`` padded chunk
  of prompt positions ``start .. start+length-1``, through each op's
  own forward arithmetic: position-wise ops run unchanged, attention
  uses :meth:`~flexflow_tpu.ops.attention.MultiHeadAttention.
  forward_paged` (scatter the chunk's K/V into the slot's pages, attend
  over the gathered page table — history written by earlier chunks or
  borrowed from the prefix cache, plus the chunk itself, causally
  masked on global positions), the LSTM ``forward_states`` (whole-
  prompt chunks only — cell state cannot page).  One jitted program per
  power-of-two chunk bucket; a single chunk covering the whole prompt
  IS the monolithic prefill, so ``serve_prefill_chunk=0`` reproduces
  the pre-paging behavior program-for-program.
* **decode** — ONE jitted step for the whole ``slots``-wide decode
  batch: embed the current token per slot, run every layer's
  single-position path, scatter K/V at each slot's
  ``(write_page, write_row)`` (host-computed; the pool's ``no_page``
  sentinel drops inactive/prefilling slots' writes), gather each
  slot's page table and attend, argmax the next token.  The cache
  pytree is donated, so XLA updates the (potentially multi-GB) pools
  in place.

Pool geometry and sharding come from
:mod:`flexflow_tpu.analysis.kv_memory` — the SAME module the static
FF108/FF121/FF130 memory gates integrate, so what lint predicts is
what this decoder allocates (the arrays themselves come from
``pages.alloc_pool_arrays``, the one allocation site RL013 pins).
Heads shard over the tensor-parallel ``c`` mesh axis; the page dim is
replicated (pages are interchangeable across slots).

Supported graphs: one (n, s) int token input; position-wise ops
(dense/norms/elementwise/softmax/dropout/embedding), causal
self-attention, stateless-init LSTM, learned position embeddings.
Anything else (convs, splits, cross-attention, MoE, pipelines) fails
validation loudly at construction — a generation engine must never
silently produce wrong tokens for an unsupported graph.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.kv_memory import (DEFAULT_PAGE_SIZE, default_num_pages,
                                   kv_cache_layout, pages_per_slot)
from ...op import OpContext, OpType
from ...ops.attention import MultiHeadAttention, PositionEmbedding
from ...ops.linear import Embedding
from ...ops.rnn import LSTM
from . import sampling
from .pages import alloc_pool_arrays

# ops that act position-wise over the sequence dim: running them on a
# (slots, 1, d) activation IS the decode step (validated per-op below)
_POINTWISE_TYPES = (OpType.LINEAR, OpType.LAYERNORM, OpType.RMSNORM,
                    OpType.ELEMENT_UNARY, OpType.ELEMENT_BINARY,
                    OpType.SOFTMAX, OpType.DROPOUT)


def prefill_buckets(max_seq: int) -> Tuple[int, ...]:
    """Power-of-two chunk buckets 2, 4, ... capped at ``max_seq``
    (always included) — one compiled prefill-chunk program per bucket.
    The floor of 2 is the matrix-vector parity rule (a 1-row program's
    bits drift ~1 ulp, like serve_buckets)."""
    out: List[int] = []
    b = 2
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(int(max_seq))
    return tuple(out)


class GraphDecoder:
    """Prefill-chunk + decode executables for one (model, slots,
    max_seq, page geometry).  Use :meth:`for_model` — instances cache
    their jitted programs, and engines sharing a geometry share the
    compiles."""

    def __init__(self, model, slots: int, max_seq: int,
                 page_size: int = 0, num_pages: int = 0):
        if slots < 2:
            raise ValueError(
                f"slots must be >= 2, got {slots}: a 1-slot decode "
                f"batch lowers matrix-vector kernels whose bits differ "
                f"from the full forward (same floor as serve_buckets)")
        self.model = model
        self.slots = int(slots)
        self.max_seq = int(max_seq)
        cfg = model.config
        self.page_size = int(page_size
                             or getattr(cfg, "serve_kv_page", 0)
                             or DEFAULT_PAGE_SIZE)
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, "
                             f"got {self.page_size}")
        self.pages_per_slot = pages_per_slot(self.max_seq, self.page_size)
        self.num_pages = int(num_pages
                             or getattr(cfg, "serve_kv_pages", 0)
                             or default_num_pages(self.slots, self.max_seq,
                                                  self.page_size))
        if self.num_pages < self.pages_per_slot:
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold even one "
                f"max_seq={self.max_seq} stream "
                f"({self.pages_per_slot} pages of {self.page_size})")
        self._validate()
        self.buckets = prefill_buckets(self.max_seq)
        mesh = model.mesh
        self._mesh_sizes = dict(mesh.sizes) if mesh is not None else None
        self.layout = kv_cache_layout(model.layers, self._mesh_sizes,
                                      self.slots, self.max_seq,
                                      page_size=self.page_size,
                                      num_pages=self.num_pages)
        self.has_attention = any(isinstance(op, MultiHeadAttention)
                                 for op in model.layers)
        self.has_state = any(isinstance(op, LSTM) for op in model.layers)
        # cell state cannot page: an LSTM chunk at offset k would need
        # the carry from chunk k-1 as a program input the stateless
        # forward_states does not take — whole-prompt chunks only, and
        # no prefix reuse (the engine enforces both)
        self.supports_chunking = not self.has_state
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fn = None
        self._decode_sampled_fn = None
        self._verify_fns: Dict[Tuple[int, bool], object] = {}
        self._draft_fns: Dict[Tuple[int, bool], object] = {}

    # ---- validation ----------------------------------------------------
    def _validate(self) -> None:
        model = self.model
        if len(model.input_tensors) != 1:
            raise ValueError(
                f"generation needs exactly one token input, model has "
                f"{len(model.input_tensors)}")
        tin = model.input_tensors[0]
        if len(tin.shape) != 2 or not np.issubdtype(np.dtype(tin.dtype),
                                                    np.integer):
            raise ValueError(
                f"generation input must be (n, s) integer token ids, "
                f"got {tin.shape} {tin.dtype}")
        self._input_uid = tin.uid
        final = getattr(model, "_final_tensor", None) or \
            model.layers[-1].outputs[0]
        if len(final.shape) != 3:
            raise ValueError(
                f"generation needs per-token (n, s, vocab) outputs, "
                f"final tensor is {final.shape} — use an LM graph "
                f"(models.build_transformer_lm / build_lstm_lm), not a "
                f"classifier")
        self._final_uid = final.uid
        for op in model.layers:
            if isinstance(op, MultiHeadAttention):
                if not (op._self_attn and op.causal):
                    raise ValueError(
                        f"{op.name}: generation needs causal "
                        f"self-attention (cross-attention/bidirectional "
                        f"blocks cannot decode autoregressively)")
            elif isinstance(op, PositionEmbedding):
                if op.max_len < self.max_seq:
                    raise ValueError(
                        f"{op.name}: position table holds {op.max_len} "
                        f"positions < max_seq {self.max_seq}")
            elif isinstance(op, LSTM):
                if op._has_state:
                    raise ValueError(
                        f"{op.name}: LSTM with an external initial_state "
                        f"is not decodable (seed states are a prefill "
                        f"product, not a graph input)")
            elif isinstance(op, Embedding):
                if op.aggr != "none":
                    raise ValueError(
                        f"{op.name}: only sequence-mode (aggr='none') "
                        f"embeddings decode; bag aggregation collapses "
                        f"the sequence dim")
            elif op.op_type not in _POINTWISE_TYPES:
                raise ValueError(
                    f"{op.name} ({op.op_type.value}) has no "
                    f"single-position decode path; generation supports "
                    f"causal attention, LSTM, embeddings and "
                    f"position-wise ops")

    # ---- shared context ------------------------------------------------
    def _ctx(self) -> OpContext:
        cfg = self.model.config
        return OpContext(
            training=False, rng=None, compute_dtype=cfg.compute_dtype,
            mesh=self.model.mesh, flash_attention=cfg.flash_attention,
            conv_layout=getattr(self.model, "resolved_conv_layout",
                                "nchw"))

    # ---- cache ---------------------------------------------------------
    def init_cache(self) -> Dict[str, Dict[str, jax.Array]]:
        """Preallocate the page pools + LSTM state, placed under the
        layout's PartitionSpecs — through ``pages.alloc_pool_arrays``,
        the ONE KV allocation site (RL013; the bytes the
        FF108/FF121/FF130 gates charge are exactly these
        allocations)."""
        return alloc_pool_arrays(self.layout, self.model.mesh,
                                 self.model.config.compute_dtype)

    # ---- prefill -------------------------------------------------------
    def prefill_bucket(self, chunk_len: int) -> int:
        """Smallest chunk bucket covering ``chunk_len``."""
        for b in self.buckets:
            if b >= chunk_len:
                return b
        raise ValueError(f"prefill chunk of {chunk_len} tokens exceeds "
                         f"max_seq {self.max_seq}")

    def prefill_fn(self, bucket: int):
        """The jitted prefill-CHUNK program for one bucket:
        ``fn(params, caches, tokens (1, bucket), table_row
        (pages_per_slot,), slot, start, length) -> (next_token,
        caches)`` — runs the forward over chunk positions ``start ..
        start+length-1``, scatters the chunk's K/V into the slot's
        pages / writes the LSTM carry at ``length - 1``, and argmaxes
        the chunk's last real position's logits.  For the FINAL chunk
        that argmax is the stream's FIRST generated token (TTFT is the
        last chunk's dispatch); intermediate chunks' return value is
        ignored.  The cache pytree is donated."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        if bucket not in self.buckets:
            raise ValueError(f"unknown prefill bucket {bucket}")
        layers = self.model.layers

        def prefill(params, caches, tokens, table_row, slot, start,
                    length):
            ctx = self._ctx()
            values: Dict[int, jax.Array] = {self._input_uid: tokens}
            new = {name: dict(sub) for name, sub in caches.items()}
            for op in layers:
                ins = [values[t.uid] for t in op.inputs]
                if isinstance(op, MultiHeadAttention):
                    outs, kp, vp = op.forward_paged(
                        params, ins[0], new[op.name]["k"],
                        new[op.name]["v"], table_row, start, length, ctx)
                    new[op.name] = {"k": kp, "v": vp}
                elif isinstance(op, LSTM):
                    # whole-prompt chunk only (supports_chunking False):
                    # start == 0, so forward_states' zero-state scan is
                    # exactly the monolithic prefill
                    outs, hs, cs = op.forward_states(params, ins, ctx)
                    h_sel = jax.lax.dynamic_index_in_dim(
                        hs, length - 1, axis=1, keepdims=False)
                    c_sel = jax.lax.dynamic_index_in_dim(
                        cs, length - 1, axis=1, keepdims=False)
                    new[op.name] = {
                        "h": jax.lax.dynamic_update_slice(
                            new[op.name]["h"], h_sel, (slot, 0)),
                        "c": jax.lax.dynamic_update_slice(
                            new[op.name]["c"], c_sel, (slot, 0)),
                    }
                elif isinstance(op, PositionEmbedding):
                    outs = op.forward_at(params, ins[0], start, ctx)
                else:
                    outs = op.forward(params, ins, ctx)
                for t, val in zip(op.outputs, outs):
                    values[t.uid] = val
            logits = values[self._final_uid]
            last = jax.lax.dynamic_index_in_dim(
                logits, length - 1, axis=1, keepdims=False)[0]
            nxt = jnp.argmax(last).astype(jnp.int32)
            return nxt, new

        fn = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_fns[bucket] = fn
        return fn

    # ---- decode --------------------------------------------------------
    def decode_fn(self):
        """THE decode step, jitted once per geometry:
        ``fn(params, caches, tokens (slots,), pos (slots,), table
        (slots, pages_per_slot), write_pages (slots,), write_rows
        (slots,)) -> (next_tokens (slots,), caches)``.  Every slot
        advances one position per call — inactive/prefilling slots
        compute on dummy inputs with ``write_pages`` at the pool's OOB
        sentinel (their scatter drops; a write through a stale table
        entry could corrupt a SHARED prefix page), which keeps the
        program shape static.  Greedy argmax decoding: deterministic,
        and exactly what the replicated ``predict``-style reference
        does — the engine==reference parity pin compares token ids."""
        if self._decode_fn is not None:
            return self._decode_fn

        def decode(params, caches, tokens, pos, table, write_pages,
                   write_rows):
            logits, new = self._walk_decode(params, caches, tokens, pos,
                                            table, write_pages,
                                            write_rows)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, new

        self._decode_fn = jax.jit(decode, donate_argnums=(1,))
        return self._decode_fn

    def decode_sampled_fn(self):
        """The SAMPLED decode step: the same layer walk as
        :meth:`decode_fn` with the argmax replaced by per-slot
        temperature/top-k/top-p sampling from the request-seeded
        on-device PRNG streams (``sampling.STREAM_MAIN`` folded with
        the GLOBAL position of the token being drawn, so the same
        (seed, request) replays the same tokens).  Slots with
        ``temperature <= 0`` get the exact one-hot argmax distribution
        — but the engine still routes ALL-greedy batches through
        :meth:`decode_fn`, so the unsampled bit-parity pins never
        depend on this program.
        ``fn(params, caches, tokens, pos, table, write_pages,
        write_rows, temp (slots,), top_k (slots,), top_p (slots,),
        seeds (slots,)) -> (next_tokens, caches)``."""
        if self._decode_sampled_fn is not None:
            return self._decode_sampled_fn

        def decode_s(params, caches, tokens, pos, table, write_pages,
                     write_rows, temp, top_k, top_p, seeds):
            logits, new = self._walk_decode(params, caches, tokens, pos,
                                            table, write_pages,
                                            write_rows)
            probs = sampling.filtered_probs(logits, temp, top_k, top_p)
            keys = sampling.position_keys(sampling.request_keys(seeds),
                                          pos + 1, sampling.STREAM_MAIN)
            nxt = sampling.categorical(keys, probs)
            return nxt, new

        self._decode_sampled_fn = jax.jit(decode_s, donate_argnums=(1,))
        return self._decode_sampled_fn

    # ---- speculative decoding (docs/serving.md "Speculative
    # decoding & sampling") ----------------------------------------------
    def _walk_decode(self, params, caches, tokens, pos, table,
                     write_pages, write_rows):
        """The shared single-position layer walk: returns the (slots,
        V) logits + updated caches (the body of :meth:`decode_fn`,
        factored so the sampled decode and the draft scan run the
        IDENTICAL arithmetic)."""
        ctx = self._ctx()
        x = tokens[:, None]                              # (slots, 1)
        values: Dict[int, jax.Array] = {self._input_uid: x}
        new: Dict[str, Dict[str, jax.Array]] = {}
        for op in self.model.layers:
            ins = [values[t.uid] for t in op.inputs]
            if isinstance(op, MultiHeadAttention):
                outs, kp, vp = op.decode_paged(
                    params, ins[0], caches[op.name]["k"],
                    caches[op.name]["v"], table, pos,
                    write_pages, write_rows, ctx)
                new[op.name] = {"k": kp, "v": vp}
            elif isinstance(op, LSTM):
                outs, h2, c2 = op.decode(
                    params, ins[0], caches[op.name]["h"],
                    caches[op.name]["c"], ctx)
                new[op.name] = {"h": h2, "c": c2}
            elif isinstance(op, PositionEmbedding):
                outs = op.decode(params, ins[0], pos, ctx)
            else:
                outs = op.forward(params, ins, ctx)
            for t, val in zip(op.outputs, outs):
                values[t.uid] = val
        return values[self._final_uid][:, 0], new        # (slots, V)

    def _walk_window(self, params, caches, window, pos, table,
                     write_pages, write_rows):
        """The W-position verify walk: ``window`` (slots, W) int32
        tokens at global positions ``pos[i] .. pos[i]+W-1`` per slot,
        through every op's window path — attention via
        :meth:`~flexflow_tpu.ops.attention.MultiHeadAttention.
        verify_paged` (the slot-batched chunked-prefill kernel),
        position embeddings via ``decode_window``, position-wise ops
        unchanged.  Returns the (slots, W, V) logits + updated caches.
        Speculation requires ``supports_chunking`` (no LSTM): a cell
        state cannot roll back to an accept point."""
        ctx = self._ctx()
        values: Dict[int, jax.Array] = {self._input_uid: window}
        new: Dict[str, Dict[str, jax.Array]] = {}
        for op in self.model.layers:
            ins = [values[t.uid] for t in op.inputs]
            if isinstance(op, MultiHeadAttention):
                outs, kp, vp = op.verify_paged(
                    params, ins[0], caches[op.name]["k"],
                    caches[op.name]["v"], table, pos,
                    write_pages, write_rows, ctx)
                new[op.name] = {"k": kp, "v": vp}
            elif isinstance(op, PositionEmbedding):
                outs = op.decode_window(params, ins[0], pos, ctx)
            else:
                outs = op.forward(params, ins, ctx)
            for t, val in zip(op.outputs, outs):
                values[t.uid] = val
        return values[self._final_uid], new              # (slots, W, V)

    def verify_fn(self, width: int, sampled: bool = False):
        """The jitted speculative-VERIFY program for one window width
        W (== the round's γ): run the target over ``[last_token, d_1,
        .., d_{W-1}]`` at positions ``pos .. pos+W-1`` per slot in ONE
        dispatch — window row t's logits decide the token at position
        ``pos+t+1``, compared against proposal ``d_{t+1}``.

        Greedy (``sampled=False``):
        ``fn(params, caches, first (slots,), d (slots, W), pos, table,
        wp (slots, W), wr (slots, W)) -> ((n_accept (slots,), out
        (slots, W)), caches)`` where ``out`` is the target argmax per
        row — rows ``< n_accept`` equal the accepted proposals and row
        ``n_accept`` (when < W) IS the correction token, so the host
        emits ``out[i, :min(n+1, W)]`` verbatim.  Bit-identical to
        sequential greedy decode by induction over accepted prefixes
        (the parity pin).

        Sampled (``sampled=True``) adds ``q (slots, W, V)`` draft
        probs + per-slot strategy arrays, and applies seeded
        rejection-sampling acceptance on device
        (:func:`sampling.speculative_accept`), preserving the target
        distribution exactly."""
        key = (int(width), bool(sampled))
        fn = self._verify_fns.get(key)
        if fn is not None:
            return fn
        if not self.supports_chunking:
            raise ValueError("speculative verify needs a chunkable "
                             "graph (LSTM state cannot roll back)")
        w = int(width)

        def verify(params, caches, first, d, pos, table, wp, wr):
            window = jnp.concatenate([first[:, None], d[:, :-1]],
                                     axis=1)
            logits, new = self._walk_window(params, caches, window, pos,
                                            table, wp, wr)
            tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            eq = (d == tgt).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(eq, axis=1),
                            axis=1).astype(jnp.int32)
            return (n_acc, tgt), new

        def verify_s(params, caches, first, d, q, pos, table, wp, wr,
                     temp, top_k, top_p, seeds):
            slots = first.shape[0]
            window = jnp.concatenate([first[:, None], d[:, :-1]],
                                     axis=1)
            logits, new = self._walk_window(params, caches, window, pos,
                                            table, wp, wr)
            flat = logits.reshape(slots * w, -1)
            rep = lambda a: jnp.repeat(a, w)
            p = sampling.filtered_probs(flat, rep(temp), rep(top_k),
                                        rep(top_p))
            p = p.reshape(slots, w, -1)
            base = jnp.repeat(sampling.request_keys(seeds), w, axis=0)
            tpos = (pos[:, None] + 1 + jnp.arange(w)).reshape(-1)
            akeys = sampling.position_keys(
                base, tpos, sampling.STREAM_ACCEPT).reshape(slots, w, 2)
            rkeys = sampling.position_keys(
                base, tpos, sampling.STREAM_RESIDUAL).reshape(slots, w,
                                                             2)
            n_acc, out = sampling.speculative_accept(d, p, q, akeys,
                                                     rkeys)
            return (n_acc, out), new

        fn = jax.jit(verify_s if sampled else verify, donate_argnums=(1,))
        self._verify_fns[key] = fn
        return fn

    def draft_fn(self, gamma: int, sampled: bool = False):
        """The jitted γ-step DRAFT program: ONE dispatch scans γ decode
        steps of the draft graph — step t feeds the token at position
        ``pos+t`` (step 0: the stream's last token; later steps: the
        previous proposal), writes the draft's K/V row there, and
        proposes the token for position ``pos+t+1``.  After the scan
        the draft cache covers exactly ``pos .. pos+γ-1`` — with the
        no-bonus-token verify window the draft is exactly caught up
        after EVERY round, accepted or not, so there is no draft
        catch-up state to track.

        Greedy: ``fn(params, caches, first (slots,), pos, table, wp
        (γ, slots), wr (γ, slots)) -> (d (slots, γ), caches)``.
        Sampled adds strategy arrays and also returns the per-step
        draft distributions ``q (slots, γ, V)`` the rejection test
        needs."""
        key = (int(gamma), bool(sampled))
        fn = self._draft_fns.get(key)
        if fn is not None:
            return fn
        if not self.supports_chunking:
            raise ValueError("speculative draft needs a chunkable "
                             "graph (LSTM state cannot roll back)")

        def draft(params, caches, first, pos, table, wp, wr):
            def step(carry, xs):
                tok, kv = carry
                wp_t, wr_t, t = xs
                logits, kv = self._walk_decode(params, kv, tok, pos + t,
                                               table, wp_t, wr_t)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, kv), nxt

            (_, new), d = jax.lax.scan(
                step, (first, caches),
                (wp, wr, jnp.arange(int(gamma))))
            return jnp.transpose(d), new                 # (slots, γ)

        def draft_s(params, caches, first, pos, table, wp, wr, temp,
                    top_k, top_p, seeds):
            base = sampling.request_keys(seeds)

            def step(carry, xs):
                tok, kv = carry
                wp_t, wr_t, t = xs
                logits, kv = self._walk_decode(params, kv, tok, pos + t,
                                               table, wp_t, wr_t)
                q = sampling.filtered_probs(logits, temp, top_k, top_p)
                keys = sampling.position_keys(base, pos + t + 1,
                                              sampling.STREAM_DRAFT)
                nxt = sampling.categorical(keys, q)
                return (nxt, kv), (nxt, q)

            (_, new), (d, q) = jax.lax.scan(
                step, (first, caches),
                (wp, wr, jnp.arange(int(gamma))))
            return (jnp.transpose(d),
                    jnp.transpose(q, (1, 0, 2))), new

        fn = jax.jit(draft_s if sampled else draft, donate_argnums=(1,))
        self._draft_fns[key] = fn
        return fn

    # ---- shared-instance registry --------------------------------------
    @classmethod
    def for_model(cls, model, slots: int, max_seq: int,
                  page_size: int = 0, num_pages: int = 0
                  ) -> "GraphDecoder":
        """One decoder per (model, slots, max_seq, page geometry):
        engines sharing a geometry share the jitted prefill/decode
        programs (the compile cost is the startup cost, like the
        serving engine's bucket warmup).  The key is the RESOLVED
        geometry, not the raw args: a 0-default key would pin the
        FIRST construction's config values (a later
        ``cfg.serve_kv_page`` change would silently get the stale
        decoder), and an explicit value equal to the default would
        duplicate identical compiles under a second key."""
        cfg = model.config
        ps = int(page_size
                 or getattr(cfg, "serve_kv_page", 0)
                 or DEFAULT_PAGE_SIZE)
        pool = int(num_pages
                   or getattr(cfg, "serve_kv_pages", 0)
                   or (default_num_pages(slots, max_seq, ps)
                       if ps > 0 else 0))
        reg = model.__dict__.setdefault("_gen_decoders", {})
        key = (int(slots), int(max_seq), ps, pool)
        dec = reg.get(key)
        if dec is None:
            dec = cls(model, slots, max_seq, page_size=ps,
                      num_pages=pool)
            reg[key] = dec
        return dec
