"""GraphDecoder — autoregressive execution of an FFModel graph over a
PAGED KV cache.

The training/serving executor runs the graph at full sequence length;
generation needs the same graph one position at a time against state
that scales with *live tokens*, not ``slots x max_seq``.  This module
derives both halves from the layer list itself:

* **prefill chunk** — the forward over a ``(1, bucket)`` padded chunk
  of prompt positions ``start .. start+length-1``, through each op's
  own forward arithmetic: position-wise ops run unchanged, attention
  uses :meth:`~flexflow_tpu.ops.attention.MultiHeadAttention.
  forward_paged` (scatter the chunk's K/V into the slot's pages, attend
  over the gathered page table — history written by earlier chunks or
  borrowed from the prefix cache, plus the chunk itself, causally
  masked on global positions), the LSTM ``forward_states`` (whole-
  prompt chunks only — cell state cannot page).  One jitted program per
  power-of-two chunk bucket; a single chunk covering the whole prompt
  IS the monolithic prefill, so ``serve_prefill_chunk=0`` reproduces
  the pre-paging behavior program-for-program.
* **decode** — ONE jitted step for the whole ``slots``-wide decode
  batch: embed the current token per slot, run every layer's
  single-position path, scatter K/V at each slot's
  ``(write_page, write_row)`` (host-computed; the pool's ``no_page``
  sentinel drops inactive/prefilling slots' writes), gather each
  slot's page table and attend, argmax the next token.  The cache
  pytree is donated, so XLA updates the (potentially multi-GB) pools
  in place.

Pool geometry and sharding come from
:mod:`flexflow_tpu.analysis.kv_memory` — the SAME module the static
FF108/FF121/FF130 memory gates integrate, so what lint predicts is
what this decoder allocates (the arrays themselves come from
``pages.alloc_pool_arrays``, the one allocation site RL013 pins).
Heads shard over the tensor-parallel ``c`` mesh axis; the page dim is
replicated (pages are interchangeable across slots).

Supported graphs: one (n, s) int token input; position-wise ops
(dense/norms/elementwise/softmax/dropout/embedding), causal
self-attention, stateless-init LSTM, learned position embeddings.
Anything else (convs, splits, cross-attention, MoE, pipelines) fails
validation loudly at construction — a generation engine must never
silently produce wrong tokens for an unsupported graph.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.kv_memory import (DEFAULT_PAGE_SIZE, default_num_pages,
                                   kv_cache_layout, pages_per_slot)
from ...op import OpContext, OpType
from ...ops.attention import MultiHeadAttention, PositionEmbedding
from ...ops.linear import Embedding
from ...ops.rnn import LSTM
from .pages import alloc_pool_arrays

# ops that act position-wise over the sequence dim: running them on a
# (slots, 1, d) activation IS the decode step (validated per-op below)
_POINTWISE_TYPES = (OpType.LINEAR, OpType.LAYERNORM, OpType.RMSNORM,
                    OpType.ELEMENT_UNARY, OpType.ELEMENT_BINARY,
                    OpType.SOFTMAX, OpType.DROPOUT)


def prefill_buckets(max_seq: int) -> Tuple[int, ...]:
    """Power-of-two chunk buckets 2, 4, ... capped at ``max_seq``
    (always included) — one compiled prefill-chunk program per bucket.
    The floor of 2 is the matrix-vector parity rule (a 1-row program's
    bits drift ~1 ulp, like serve_buckets)."""
    out: List[int] = []
    b = 2
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(int(max_seq))
    return tuple(out)


class GraphDecoder:
    """Prefill-chunk + decode executables for one (model, slots,
    max_seq, page geometry).  Use :meth:`for_model` — instances cache
    their jitted programs, and engines sharing a geometry share the
    compiles."""

    def __init__(self, model, slots: int, max_seq: int,
                 page_size: int = 0, num_pages: int = 0):
        if slots < 2:
            raise ValueError(
                f"slots must be >= 2, got {slots}: a 1-slot decode "
                f"batch lowers matrix-vector kernels whose bits differ "
                f"from the full forward (same floor as serve_buckets)")
        self.model = model
        self.slots = int(slots)
        self.max_seq = int(max_seq)
        cfg = model.config
        self.page_size = int(page_size
                             or getattr(cfg, "serve_kv_page", 0)
                             or DEFAULT_PAGE_SIZE)
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, "
                             f"got {self.page_size}")
        self.pages_per_slot = pages_per_slot(self.max_seq, self.page_size)
        self.num_pages = int(num_pages
                             or getattr(cfg, "serve_kv_pages", 0)
                             or default_num_pages(self.slots, self.max_seq,
                                                  self.page_size))
        if self.num_pages < self.pages_per_slot:
            raise ValueError(
                f"num_pages {self.num_pages} cannot hold even one "
                f"max_seq={self.max_seq} stream "
                f"({self.pages_per_slot} pages of {self.page_size})")
        self._validate()
        self.buckets = prefill_buckets(self.max_seq)
        mesh = model.mesh
        self._mesh_sizes = dict(mesh.sizes) if mesh is not None else None
        self.layout = kv_cache_layout(model.layers, self._mesh_sizes,
                                      self.slots, self.max_seq,
                                      page_size=self.page_size,
                                      num_pages=self.num_pages)
        self.has_attention = any(isinstance(op, MultiHeadAttention)
                                 for op in model.layers)
        self.has_state = any(isinstance(op, LSTM) for op in model.layers)
        # cell state cannot page: an LSTM chunk at offset k would need
        # the carry from chunk k-1 as a program input the stateless
        # forward_states does not take — whole-prompt chunks only, and
        # no prefix reuse (the engine enforces both)
        self.supports_chunking = not self.has_state
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fn = None

    # ---- validation ----------------------------------------------------
    def _validate(self) -> None:
        model = self.model
        if len(model.input_tensors) != 1:
            raise ValueError(
                f"generation needs exactly one token input, model has "
                f"{len(model.input_tensors)}")
        tin = model.input_tensors[0]
        if len(tin.shape) != 2 or not np.issubdtype(np.dtype(tin.dtype),
                                                    np.integer):
            raise ValueError(
                f"generation input must be (n, s) integer token ids, "
                f"got {tin.shape} {tin.dtype}")
        self._input_uid = tin.uid
        final = getattr(model, "_final_tensor", None) or \
            model.layers[-1].outputs[0]
        if len(final.shape) != 3:
            raise ValueError(
                f"generation needs per-token (n, s, vocab) outputs, "
                f"final tensor is {final.shape} — use an LM graph "
                f"(models.build_transformer_lm / build_lstm_lm), not a "
                f"classifier")
        self._final_uid = final.uid
        for op in model.layers:
            if isinstance(op, MultiHeadAttention):
                if not (op._self_attn and op.causal):
                    raise ValueError(
                        f"{op.name}: generation needs causal "
                        f"self-attention (cross-attention/bidirectional "
                        f"blocks cannot decode autoregressively)")
            elif isinstance(op, PositionEmbedding):
                if op.max_len < self.max_seq:
                    raise ValueError(
                        f"{op.name}: position table holds {op.max_len} "
                        f"positions < max_seq {self.max_seq}")
            elif isinstance(op, LSTM):
                if op._has_state:
                    raise ValueError(
                        f"{op.name}: LSTM with an external initial_state "
                        f"is not decodable (seed states are a prefill "
                        f"product, not a graph input)")
            elif isinstance(op, Embedding):
                if op.aggr != "none":
                    raise ValueError(
                        f"{op.name}: only sequence-mode (aggr='none') "
                        f"embeddings decode; bag aggregation collapses "
                        f"the sequence dim")
            elif op.op_type not in _POINTWISE_TYPES:
                raise ValueError(
                    f"{op.name} ({op.op_type.value}) has no "
                    f"single-position decode path; generation supports "
                    f"causal attention, LSTM, embeddings and "
                    f"position-wise ops")

    # ---- shared context ------------------------------------------------
    def _ctx(self) -> OpContext:
        cfg = self.model.config
        return OpContext(
            training=False, rng=None, compute_dtype=cfg.compute_dtype,
            mesh=self.model.mesh, flash_attention=cfg.flash_attention,
            conv_layout=getattr(self.model, "resolved_conv_layout",
                                "nchw"))

    # ---- cache ---------------------------------------------------------
    def init_cache(self) -> Dict[str, Dict[str, jax.Array]]:
        """Preallocate the page pools + LSTM state, placed under the
        layout's PartitionSpecs — through ``pages.alloc_pool_arrays``,
        the ONE KV allocation site (RL013; the bytes the
        FF108/FF121/FF130 gates charge are exactly these
        allocations)."""
        return alloc_pool_arrays(self.layout, self.model.mesh,
                                 self.model.config.compute_dtype)

    # ---- prefill -------------------------------------------------------
    def prefill_bucket(self, chunk_len: int) -> int:
        """Smallest chunk bucket covering ``chunk_len``."""
        for b in self.buckets:
            if b >= chunk_len:
                return b
        raise ValueError(f"prefill chunk of {chunk_len} tokens exceeds "
                         f"max_seq {self.max_seq}")

    def prefill_fn(self, bucket: int):
        """The jitted prefill-CHUNK program for one bucket:
        ``fn(params, caches, tokens (1, bucket), table_row
        (pages_per_slot,), slot, start, length) -> (next_token,
        caches)`` — runs the forward over chunk positions ``start ..
        start+length-1``, scatters the chunk's K/V into the slot's
        pages / writes the LSTM carry at ``length - 1``, and argmaxes
        the chunk's last real position's logits.  For the FINAL chunk
        that argmax is the stream's FIRST generated token (TTFT is the
        last chunk's dispatch); intermediate chunks' return value is
        ignored.  The cache pytree is donated."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        if bucket not in self.buckets:
            raise ValueError(f"unknown prefill bucket {bucket}")
        layers = self.model.layers

        def prefill(params, caches, tokens, table_row, slot, start,
                    length):
            ctx = self._ctx()
            values: Dict[int, jax.Array] = {self._input_uid: tokens}
            new = {name: dict(sub) for name, sub in caches.items()}
            for op in layers:
                ins = [values[t.uid] for t in op.inputs]
                if isinstance(op, MultiHeadAttention):
                    outs, kp, vp = op.forward_paged(
                        params, ins[0], new[op.name]["k"],
                        new[op.name]["v"], table_row, start, length, ctx)
                    new[op.name] = {"k": kp, "v": vp}
                elif isinstance(op, LSTM):
                    # whole-prompt chunk only (supports_chunking False):
                    # start == 0, so forward_states' zero-state scan is
                    # exactly the monolithic prefill
                    outs, hs, cs = op.forward_states(params, ins, ctx)
                    h_sel = jax.lax.dynamic_index_in_dim(
                        hs, length - 1, axis=1, keepdims=False)
                    c_sel = jax.lax.dynamic_index_in_dim(
                        cs, length - 1, axis=1, keepdims=False)
                    new[op.name] = {
                        "h": jax.lax.dynamic_update_slice(
                            new[op.name]["h"], h_sel, (slot, 0)),
                        "c": jax.lax.dynamic_update_slice(
                            new[op.name]["c"], c_sel, (slot, 0)),
                    }
                elif isinstance(op, PositionEmbedding):
                    outs = op.forward_at(params, ins[0], start, ctx)
                else:
                    outs = op.forward(params, ins, ctx)
                for t, val in zip(op.outputs, outs):
                    values[t.uid] = val
            logits = values[self._final_uid]
            last = jax.lax.dynamic_index_in_dim(
                logits, length - 1, axis=1, keepdims=False)[0]
            nxt = jnp.argmax(last).astype(jnp.int32)
            return nxt, new

        fn = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_fns[bucket] = fn
        return fn

    # ---- decode --------------------------------------------------------
    def decode_fn(self):
        """THE decode step, jitted once per geometry:
        ``fn(params, caches, tokens (slots,), pos (slots,), table
        (slots, pages_per_slot), write_pages (slots,), write_rows
        (slots,)) -> (next_tokens (slots,), caches)``.  Every slot
        advances one position per call — inactive/prefilling slots
        compute on dummy inputs with ``write_pages`` at the pool's OOB
        sentinel (their scatter drops; a write through a stale table
        entry could corrupt a SHARED prefix page), which keeps the
        program shape static.  Greedy argmax decoding: deterministic,
        and exactly what the replicated ``predict``-style reference
        does — the engine==reference parity pin compares token ids."""
        if self._decode_fn is not None:
            return self._decode_fn
        layers = self.model.layers

        def decode(params, caches, tokens, pos, table, write_pages,
                   write_rows):
            ctx = self._ctx()
            x = tokens[:, None]                          # (slots, 1)
            values: Dict[int, jax.Array] = {self._input_uid: x}
            new: Dict[str, Dict[str, jax.Array]] = {}
            for op in layers:
                ins = [values[t.uid] for t in op.inputs]
                if isinstance(op, MultiHeadAttention):
                    outs, kp, vp = op.decode_paged(
                        params, ins[0], caches[op.name]["k"],
                        caches[op.name]["v"], table, pos,
                        write_pages, write_rows, ctx)
                    new[op.name] = {"k": kp, "v": vp}
                elif isinstance(op, LSTM):
                    outs, h2, c2 = op.decode(
                        params, ins[0], caches[op.name]["h"],
                        caches[op.name]["c"], ctx)
                    new[op.name] = {"h": h2, "c": c2}
                elif isinstance(op, PositionEmbedding):
                    outs = op.decode(params, ins[0], pos, ctx)
                else:
                    outs = op.forward(params, ins, ctx)
                for t, val in zip(op.outputs, outs):
                    values[t.uid] = val
            logits = values[self._final_uid][:, 0]       # (slots, V)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, new

        self._decode_fn = jax.jit(decode, donate_argnums=(1,))
        return self._decode_fn

    # ---- shared-instance registry --------------------------------------
    @classmethod
    def for_model(cls, model, slots: int, max_seq: int,
                  page_size: int = 0, num_pages: int = 0
                  ) -> "GraphDecoder":
        """One decoder per (model, slots, max_seq, page geometry):
        engines sharing a geometry share the jitted prefill/decode
        programs (the compile cost is the startup cost, like the
        serving engine's bucket warmup).  The key is the RESOLVED
        geometry, not the raw args: a 0-default key would pin the
        FIRST construction's config values (a later
        ``cfg.serve_kv_page`` change would silently get the stale
        decoder), and an explicit value equal to the default would
        duplicate identical compiles under a second key."""
        cfg = model.config
        ps = int(page_size
                 or getattr(cfg, "serve_kv_page", 0)
                 or DEFAULT_PAGE_SIZE)
        pool = int(num_pages
                   or getattr(cfg, "serve_kv_pages", 0)
                   or (default_num_pages(slots, max_seq, ps)
                       if ps > 0 else 0))
        reg = model.__dict__.setdefault("_gen_decoders", {})
        key = (int(slots), int(max_seq), ps, pool)
        dec = reg.get(key)
        if dec is None:
            dec = cls(model, slots, max_seq, page_size=ps,
                      num_pages=pool)
            reg[key] = dec
        return dec
