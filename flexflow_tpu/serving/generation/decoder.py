"""GraphDecoder — autoregressive execution of an FFModel graph.

The training/serving executor runs the graph at full sequence length;
generation needs the same graph one position at a time.  This module
derives both halves from the layer list itself:

* **prefill** — the full forward over a (1, bucket) padded prompt,
  through each op's own forward arithmetic (attention uses
  ``forward_kv``, the LSTM ``forward_states`` — bit-identical to
  ``forward``), while capturing the per-position K/V (attention) and
  per-step (h, c) (LSTM) the decode cache is seeded from.  Bucketed:
  one AOT-style jitted program per power-of-two prompt bucket, like the
  serving engine's shape buckets.
* **decode** — ONE jitted step for the whole ``slots``-wide decode
  batch: embed the current token per slot, run every layer's
  single-position path (``Op.decode``), write K/V at each slot's
  position, argmax the next token.  The cache pytree is donated, so
  XLA updates the (potentially multi-GB) buffers in place.

Cache geometry and sharding come from
:mod:`flexflow_tpu.analysis.kv_memory` — the SAME module the static
FF108/FF121 memory gates integrate, so what lint predicts is what this
decoder allocates.  Heads shard over the tensor-parallel ``c`` mesh
axis, slots over the data axis ``n`` (never below 2 slots/shard — the
matrix-vector parity rule).

Supported graphs: one (n, s) int token input; position-wise ops
(dense/norms/elementwise/softmax/dropout/embedding), causal
self-attention, stateless-init LSTM, learned position embeddings.
Anything else (convs, splits, cross-attention, MoE, pipelines) fails
validation loudly at construction — a generation engine must never
silently produce wrong tokens for an unsupported graph.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...analysis.kv_memory import kv_cache_layout
from ...op import OpContext, OpType
from ...ops.attention import MultiHeadAttention, PositionEmbedding
from ...ops.linear import Embedding
from ...ops.rnn import LSTM

# ops that act position-wise over the sequence dim: running them on a
# (slots, 1, d) activation IS the decode step (validated per-op below)
_POINTWISE_TYPES = (OpType.LINEAR, OpType.LAYERNORM, OpType.RMSNORM,
                    OpType.ELEMENT_UNARY, OpType.ELEMENT_BINARY,
                    OpType.SOFTMAX, OpType.DROPOUT)


def prefill_buckets(max_seq: int) -> Tuple[int, ...]:
    """Power-of-two prompt buckets 2, 4, ... capped at ``max_seq``
    (always included) — one compiled prefill program per bucket."""
    out: List[int] = []
    b = 2
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(int(max_seq))
    return tuple(out)


class GraphDecoder:
    """Prefill + decode executables for one (model, slots, max_seq)
    geometry.  Use :meth:`for_model` — instances cache their jitted
    programs, and engines sharing a geometry share the compiles."""

    def __init__(self, model, slots: int, max_seq: int):
        if slots < 2:
            raise ValueError(
                f"slots must be >= 2, got {slots}: a 1-slot decode "
                f"batch lowers matrix-vector kernels whose bits differ "
                f"from the full forward (same floor as serve_buckets)")
        self.model = model
        self.slots = int(slots)
        self.max_seq = int(max_seq)
        self._validate()
        self.buckets = prefill_buckets(self.max_seq)
        mesh = model.mesh
        self._mesh_sizes = dict(mesh.sizes) if mesh is not None else None
        self.layout = kv_cache_layout(model.layers, self._mesh_sizes,
                                      self.slots, self.max_seq)
        self._prefill_fns: Dict[int, object] = {}
        self._decode_fn = None

    # ---- validation ----------------------------------------------------
    def _validate(self) -> None:
        model = self.model
        if len(model.input_tensors) != 1:
            raise ValueError(
                f"generation needs exactly one token input, model has "
                f"{len(model.input_tensors)}")
        tin = model.input_tensors[0]
        if len(tin.shape) != 2 or not np.issubdtype(np.dtype(tin.dtype),
                                                    np.integer):
            raise ValueError(
                f"generation input must be (n, s) integer token ids, "
                f"got {tin.shape} {tin.dtype}")
        self._input_uid = tin.uid
        final = getattr(model, "_final_tensor", None) or \
            model.layers[-1].outputs[0]
        if len(final.shape) != 3:
            raise ValueError(
                f"generation needs per-token (n, s, vocab) outputs, "
                f"final tensor is {final.shape} — use an LM graph "
                f"(models.build_transformer_lm / build_lstm_lm), not a "
                f"classifier")
        self._final_uid = final.uid
        for op in model.layers:
            if isinstance(op, MultiHeadAttention):
                if not (op._self_attn and op.causal):
                    raise ValueError(
                        f"{op.name}: generation needs causal "
                        f"self-attention (cross-attention/bidirectional "
                        f"blocks cannot decode autoregressively)")
            elif isinstance(op, PositionEmbedding):
                if op.max_len < self.max_seq:
                    raise ValueError(
                        f"{op.name}: position table holds {op.max_len} "
                        f"positions < max_seq {self.max_seq}")
            elif isinstance(op, LSTM):
                if op._has_state:
                    raise ValueError(
                        f"{op.name}: LSTM with an external initial_state "
                        f"is not decodable (seed states are a prefill "
                        f"product, not a graph input)")
            elif isinstance(op, Embedding):
                if op.aggr != "none":
                    raise ValueError(
                        f"{op.name}: only sequence-mode (aggr='none') "
                        f"embeddings decode; bag aggregation collapses "
                        f"the sequence dim")
            elif op.op_type not in _POINTWISE_TYPES:
                raise ValueError(
                    f"{op.name} ({op.op_type.value}) has no "
                    f"single-position decode path; generation supports "
                    f"causal attention, LSTM, embeddings and "
                    f"position-wise ops")

    # ---- shared context ------------------------------------------------
    def _ctx(self) -> OpContext:
        cfg = self.model.config
        return OpContext(
            training=False, rng=None, compute_dtype=cfg.compute_dtype,
            mesh=self.model.mesh, flash_attention=cfg.flash_attention,
            conv_layout=getattr(self.model, "resolved_conv_layout",
                                "nchw"))

    # ---- cache ---------------------------------------------------------
    def init_cache(self) -> Dict[str, Dict[str, jax.Array]]:
        """Preallocate the per-slot decode state, placed under the
        layout's PartitionSpecs (analysis.kv_memory — the bytes the
        FF108/FF121 gates charge are exactly these allocations)."""
        from jax.sharding import PartitionSpec

        mesh = self.model.mesh
        compute_dt = jnp.dtype(self.model.config.compute_dtype)
        caches: Dict[str, Dict[str, jax.Array]] = {}
        for name, ent in self.layout.items():
            dt = compute_dt if ent["dtype"] == "compute" else jnp.float32
            sub: Dict[str, jax.Array] = {}
            for leaf, shape in ent["shapes"].items():
                arr = jnp.zeros(shape, dt)
                if mesh is not None and mesh.is_distributed:
                    arr = jax.device_put(
                        arr,
                        mesh.sharding(PartitionSpec(
                            *ent["entries"][leaf])))
                sub[leaf] = arr
            caches[name] = sub
        return caches

    # ---- prefill -------------------------------------------------------
    def prefill_bucket(self, prompt_len: int) -> int:
        """Smallest prompt bucket covering ``prompt_len``."""
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(f"prompt of {prompt_len} tokens exceeds "
                         f"max_seq {self.max_seq}")

    def _walk_prefill(self, params, tokens):
        """Full forward over (1, bucket) tokens, collecting each
        cache-bearing op's seed tensors.  Runs the ops' OWN forward
        arithmetic (forward_kv/forward_states are forward plus extra
        outputs), so prefill == the training executor's forward."""
        ctx = self._ctx()
        values: Dict[int, jax.Array] = {self._input_uid: tokens}
        seeds: Dict[str, Dict[str, jax.Array]] = {}
        for op in self.model.layers:
            ins = [values[t.uid] for t in op.inputs]
            if isinstance(op, MultiHeadAttention):
                outs, k, v = op.forward_kv(params, ins, ctx)
                seeds[op.name] = {"k": k, "v": v}
            elif isinstance(op, LSTM):
                outs, hs, cs = op.forward_states(params, ins, ctx)
                seeds[op.name] = {"hs": hs, "cs": cs}
            else:
                outs = op.forward(params, ins, ctx)
            for t, val in zip(op.outputs, outs):
                values[t.uid] = val
        return values[self._final_uid], seeds

    def prefill_fn(self, bucket: int):
        """The jitted prefill program for one prompt bucket:
        ``fn(params, caches, tokens (1, bucket), slot, length) ->
        (first_token, caches)`` — runs the full forward, writes the
        slot's K/V rows / gathers its (h, c) at ``length - 1``, and
        argmaxes the last prompt position's logits (the stream's FIRST
        generated token, so TTFT is one prefill dispatch).  The cache
        pytree is donated."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        if bucket not in self.buckets:
            raise ValueError(f"unknown prefill bucket {bucket}")

        def prefill(params, caches, tokens, slot, length):
            logits, seeds = self._walk_prefill(params, tokens)
            new = {name: dict(sub) for name, sub in caches.items()}
            for name, seed in seeds.items():
                if "k" in seed:
                    new[name]["k"] = jax.lax.dynamic_update_slice(
                        new[name]["k"], seed["k"], (slot, 0, 0, 0))
                    new[name]["v"] = jax.lax.dynamic_update_slice(
                        new[name]["v"], seed["v"], (slot, 0, 0, 0))
                else:
                    h_sel = jax.lax.dynamic_index_in_dim(
                        seed["hs"], length - 1, axis=1, keepdims=False)
                    c_sel = jax.lax.dynamic_index_in_dim(
                        seed["cs"], length - 1, axis=1, keepdims=False)
                    new[name]["h"] = jax.lax.dynamic_update_slice(
                        new[name]["h"], h_sel, (slot, 0))
                    new[name]["c"] = jax.lax.dynamic_update_slice(
                        new[name]["c"], c_sel, (slot, 0))
            last = jax.lax.dynamic_index_in_dim(
                logits, length - 1, axis=1, keepdims=False)[0]
            first = jnp.argmax(last).astype(jnp.int32)
            return first, new

        fn = jax.jit(prefill, donate_argnums=(1,))
        self._prefill_fns[bucket] = fn
        return fn

    # ---- decode --------------------------------------------------------
    def decode_fn(self):
        """THE decode step, jitted once per geometry:
        ``fn(params, caches, tokens (slots,), pos (slots,)) ->
        (next_tokens (slots,), caches)``.  Every slot advances one
        position per call — inactive slots compute on dummy inputs
        (their cache rows are dead and rewritten at the next prefill),
        which keeps the program shape static.  Greedy argmax decoding:
        deterministic, and exactly what the replicated
        ``predict``-style reference does — the engine==reference parity
        pin compares token ids."""
        if self._decode_fn is not None:
            return self._decode_fn
        layers = self.model.layers

        def decode(params, caches, tokens, pos):
            ctx = self._ctx()
            x = tokens[:, None]                          # (slots, 1)
            values: Dict[int, jax.Array] = {self._input_uid: x}
            new: Dict[str, Dict[str, jax.Array]] = {}
            for op in layers:
                ins = [values[t.uid] for t in op.inputs]
                if isinstance(op, MultiHeadAttention):
                    outs, k2, v2 = op.decode(
                        params, ins[0], caches[op.name]["k"],
                        caches[op.name]["v"], pos, ctx)
                    new[op.name] = {"k": k2, "v": v2}
                elif isinstance(op, LSTM):
                    outs, h2, c2 = op.decode(
                        params, ins[0], caches[op.name]["h"],
                        caches[op.name]["c"], ctx)
                    new[op.name] = {"h": h2, "c": c2}
                elif isinstance(op, PositionEmbedding):
                    outs = op.decode(params, ins[0], pos, ctx)
                else:
                    outs = op.forward(params, ins, ctx)
                for t, val in zip(op.outputs, outs):
                    values[t.uid] = val
            logits = values[self._final_uid][:, 0]       # (slots, V)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return nxt, new

        self._decode_fn = jax.jit(decode, donate_argnums=(1,))
        return self._decode_fn

    # ---- shared-instance registry --------------------------------------
    @classmethod
    def for_model(cls, model, slots: int, max_seq: int) -> "GraphDecoder":
        """One decoder per (model, slots, max_seq): engines sharing a
        geometry share the jitted prefill/decode programs (the compile
        cost is the startup cost, like the serving engine's bucket
        warmup)."""
        reg = model.__dict__.setdefault("_gen_decoders", {})
        key = (int(slots), int(max_seq))
        dec = reg.get(key)
        if dec is None:
            dec = cls(model, slots, max_seq)
            reg[key] = dec
        return dec
