"""Serving observability: rolling QPS, batch occupancy, queue depth and
latency percentiles, emitted as JSON events through the existing
fflogger machinery (one ``serve_stats`` line per reporting interval —
the same one-parseable-line-per-record contract as fit()'s ``epoch``
events).

Quantiles come from :func:`flexflow_tpu.profiling.quantiles`
(nearest-rank — every reported p50/p95/p99 is a latency that actually
happened).  All state is windowed/bounded: a week-long serving process
must not grow its metrics memory with traffic.

Overload accounting (docs/serving.md "Overload, SLOs & degradation"):
``rejected`` / ``shed`` / ``expired`` lifetime counters classify every
load-management failure by its typed exception
(:mod:`flexflow_tpu.serving.errors`), ``admission_blocked_ms``
accumulates producer time spent blocked for admission, and
``deadline_p99_ms`` tracks the latency tail of the requests that
carried a deadline — the SLO-attainment gauge.  The windowed drop rate
(``drop_stats``) feeds the engine's ``degraded`` health transition.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ..fflogger import get_logger
from ..obs import lockwatch
from ..obs.registry import get_registry
from ..obs.trace import phase_of
from ..profiling import quantiles

# per-process engine-generation sequence: the ``eng`` label that keeps
# two engines serving the SAME model name (bench legs, a fleet swap's
# old/new generation) from merging their registry counters
_ENG_SEQ = [0]
_ENG_LOCK = lockwatch.lock("metrics._ENG_LOCK")


def next_engine_id() -> str:
    """Draw the next per-process ``eng`` label value (also used by the
    FleetEngine for its fleet-scoped families — one sequence, so any
    engine-shaped thing in the process gets a unique generation id)."""
    with _ENG_LOCK:
        _ENG_SEQ[0] += 1
        return str(_ENG_SEQ[0])


def _lifetime_counters(model_tag: str):
    """Declare (idempotently) the serving counter families and return
    this engine's children.  These ARE the lifetime counters: the
    ``serve_stats`` event stream and ``stats()`` snapshots read them
    back, so the JSON events and the Prometheus ``/metrics`` exposition
    are views over one set of numbers and cannot diverge
    (docs/observability.md "Metrics")."""
    reg = get_registry()
    labels = ("model", "eng")
    eng = next_engine_id()
    kv = {"model": model_tag, "eng": eng}
    fams = {
        "submitted": reg.counter(
            "ff_serve_submitted_total",
            "Logical requests entering submit(), admitted or not",
            labels),
        "requests": reg.counter(
            "ff_serve_requests_total",
            "Logical requests completed successfully", labels),
        "rows": reg.counter(
            "ff_serve_rows_total", "Rows dispatched to the device",
            labels),
        "dispatches": reg.counter(
            "ff_serve_dispatches_total", "Packed device dispatches",
            labels),
        "errors": reg.counter(
            "ff_serve_errors_total",
            "Logical requests failed by dispatch errors", labels),
        "rejected": reg.counter(
            "ff_serve_rejected_total",
            "Requests refused at admission (OverloadError)", labels),
        "shed": reg.counter(
            "ff_serve_shed_total",
            "Queued requests evicted under overload (SheddedError)",
            labels),
        "expired": reg.counter(
            "ff_serve_expired_total",
            "Queued requests past their deadline (DeadlineExceeded)",
            labels),
        "cancelled": reg.counter(
            "ff_serve_cancelled_total",
            "Streams cancelled by the client (GenerationCancelled)",
            labels),
        "blocked_s": reg.counter(
            "ff_serve_admission_blocked_seconds_total",
            "Producer seconds spent blocked for admission", labels),
    }
    fams["latency"] = reg.histogram(
        "ff_serve_latency_seconds",
        "Logical request latency, submit to resolution", labels)
    fams["queue_depth"] = reg.gauge(
        "ff_serve_queue_depth",
        "Live pending requests in the micro-batcher", labels)
    children = {k: fam.labels(**kv) for k, fam in fams.items()}
    return children, fams, kv, eng


class ServingMetrics:
    """Thread-safe rolling serving statistics.

    Dispatch-side records (`record_dispatch`) come from the dispatcher
    thread, one per packed batch; request-side records
    (`record_request`) fire when a logical request's future resolves.
    `snapshot()` reduces the rolling window to the flat dict that both
    the ``serve_stats`` JSON event and serve-bench report.

    ``queue_depth_fn`` (settable after construction) makes the reported
    queue depth LIVE: without it, depth freezes at the last dispatch —
    a wedged dispatcher behind a growing queue would look healthy.  The
    engine points it at ``batcher.queue_depth``; ``last_dispatch_age_s``
    is the stall gauge's other half."""

    def __init__(self, window_s: float = 30.0, max_latency_samples: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 model: str = ""):
        self.window_s = float(window_s)
        self.clock = clock
        self.queue_depth_fn = queue_depth_fn
        # tenant identity: every snapshot/serve_stats row carries
        # ``model=<name>`` so two engines in one process (a model
        # fleet) emit distinguishable event streams —
        # calibration.harvest_serve_dispatch keys its dispatch entries
        # on it ("" = the pre-fleet single-engine default)
        self.model_tag = str(model)
        # lifetime counters live in the process metrics registry
        # (obs.registry): snapshot()/serve_stats READ them back — one
        # set of numbers behind both the event stream and /metrics
        self._ctr, self._fams, self._label_kv, self.eng_id = \
            _lifetime_counters(self.model_tag)
        self._ctr["queue_depth"].set_fn(
            lambda: (self.queue_depth_fn() if self.queue_depth_fn
                     else 0))
        self._released = False
        self._lock = lockwatch.lock("ServingMetrics._lock")
        # every rolling-window structure and counter below is
        # guarded_by self._lock (RL009): records arrive from producer
        # threads AND the dispatcher concurrently
        # (t, rows, bucket, n_reqs, dispatch_s) per packed batch
        self._dispatches: deque = deque()  # guarded_by: self._lock
        # (t, latency_s) per completed logical request
        self._latencies: deque = deque(  # guarded_by: self._lock
            maxlen=max_latency_samples)
        # (t, latency_s) for the subset that carried a deadline — the
        # SLO-attainment population deadline_p99_ms reports on
        self._deadline_lats: deque = deque(  # guarded_by: self._lock
            maxlen=max_latency_samples)
        # (t, n) windowed admission/drop event streams for the health
        # state machine's shed-rate threshold, with RUNNING sums so
        # drop_stats() is O(1) on the hot dispatcher path; trimmed on
        # every append (not only on reads) and hard-capped so a wedged
        # dispatcher under a submit storm cannot grow metrics memory
        self._submit_ts: deque = deque()  # guarded_by: self._lock
        self._drop_ts: deque = deque()    # guarded_by: self._lock
        self._submit_n = 0   # guarded_by: self._lock
        self._drop_n = 0     # guarded_by: self._lock
        self._queue_depth = 0  # guarded_by: self._lock
        # the dispatcher's heartbeat: last dispatch completion time,
        # the stall gauge last_dispatch_age_s reads
        self._last_dispatch_t: Optional[float] = None  # guarded_by: self._lock

    # lifetime counters: views over the registry children (each child
    # synchronizes itself) — the serve_stats/stats() population and the
    # Prometheus exposition are the SAME numbers by construction
    @property
    def total_submitted(self) -> int:
        return int(self._ctr["submitted"].value)

    @property
    def total_dispatches(self) -> int:
        return int(self._ctr["dispatches"].value)

    @property
    def total_requests(self) -> int:
        return int(self._ctr["requests"].value)

    @property
    def total_rows(self) -> int:
        return int(self._ctr["rows"].value)

    @property
    def total_errors(self) -> int:
        return int(self._ctr["errors"].value)

    @property
    def total_rejected(self) -> int:
        return int(self._ctr["rejected"].value)

    @property
    def total_shed(self) -> int:
        return int(self._ctr["shed"].value)

    @property
    def total_expired(self) -> int:
        return int(self._ctr["expired"].value)

    @property
    def total_cancelled(self) -> int:
        return int(self._ctr["cancelled"].value)

    @property
    def blocked_ms_total(self) -> float:
        return self._ctr["blocked_s"].value * 1e3

    # hard cap on windowed admission/drop EVENTS (not requests — each
    # entry may carry n>1): bounds memory even when the window itself
    # would hold more
    _MAX_WINDOW_EVENTS = 65536

    # ---- recording -----------------------------------------------------
    def _trim(self, now: float) -> None:  # guarded_by: self._lock
        horizon = now - self.window_s
        for dq in (self._dispatches, self._latencies, self._deadline_lats):
            while dq and dq[0][0] < horizon:
                dq.popleft()
        while self._submit_ts and (self._submit_ts[0][0] < horizon
                                   or len(self._submit_ts)
                                   > self._MAX_WINDOW_EVENTS):
            self._submit_n -= self._submit_ts.popleft()[1]
        while self._drop_ts and (self._drop_ts[0][0] < horizon
                                 or len(self._drop_ts)
                                 > self._MAX_WINDOW_EVENTS):
            self._drop_n -= self._drop_ts.popleft()[1]

    def record_dispatch(self, rows: int, bucket: int, n_reqs: int,
                        queue_depth: int, dispatch_s: float) -> None:
        now = self.clock()
        self._ctr["dispatches"].inc()
        self._ctr["rows"].inc(rows)
        with self._lock:
            self._dispatches.append((now, rows, bucket, n_reqs, dispatch_s))
            self._queue_depth = queue_depth
            self._last_dispatch_t = now
            self._trim(now)

    def record_request(self, latency_s: float,
                       deadlined: bool = False) -> None:
        now = self.clock()
        self._ctr["requests"].inc()
        self._ctr["latency"].observe(latency_s)
        with self._lock:
            self._latencies.append((now, latency_s))
            if deadlined:
                self._deadline_lats.append((now, latency_s))

    def record_submitted(self, n: int = 1) -> None:
        """Offered-load denominator for the windowed drop rate: one per
        LOGICAL request entering submit(), admitted or not."""
        now = self.clock()
        self._ctr["submitted"].inc(n)
        with self._lock:
            self._submit_ts.append((now, int(n)))
            self._submit_n += int(n)
            self._trim(now)

    def record_rejected(self, n: int = 1) -> None:
        """Requests refused at admission (OverloadError from submit —
        they never queued, so no future carries the failure)."""
        now = self.clock()
        self._ctr["rejected"].inc(n)
        with self._lock:
            self._drop_ts.append((now, int(n)))
            self._drop_n += int(n)
            self._trim(now)

    def record_blocked(self, seconds: float) -> None:
        """Producer time spent blocked for admission (`block` policy) —
        invisible in latency percentiles (the request had not been
        submitted yet) but very visible to the caller."""
        self._ctr["blocked_s"].inc(float(seconds))

    def record_cancelled(self, n: int = 1) -> None:
        """A client cancelled a QUEUED request before the engine ever
        claimed it: no future resolution carries an exception, but the
        request WAS submitted — without this the
        ``submitted == requests + ... + cancelled`` reconciliation
        (and its terminal-span mirror) would leak one per cancel."""
        self._ctr["cancelled"].inc(n)

    def record_failure(self, exc: BaseException) -> None:
        """Count the exception that resolved a LOGICAL request's
        future.  The classification IS ``obs.trace.phase_of`` — the
        same chain that names the terminal span's phase — so the
        counters and the trace cannot disagree about an outcome.
        Expiry/shedding are load management (their own counters; sheds
        and rejects feed the windowed drop rate), client cancels are
        not dispatch failures, anything unrecognized is an error.
        Split chunks count their request once — the caller only
        invokes this for the completion that actually resolved the
        future, so the population matches every other per-request
        metric."""
        now = self.clock()
        phase = phase_of(exc)
        if phase in ("shed", "rejected"):
            # `rejected` here is the anomalous resolved-future case
            # (admission rejects raise synchronously and never build a
            # future) — counted as rejected so both surfaces agree
            self._ctr[phase].inc()
            with self._lock:
                self._drop_ts.append((now, 1))
                self._drop_n += 1
                self._trim(now)
        elif phase in ("expired", "cancelled"):
            self._ctr[phase].inc()
        else:
            self._ctr["errors"].inc()

    def release(self) -> None:
        """Retire this metrics object's LIVE hooks from the process
        registry: freeze the queue-depth gauge at its final value and
        drop the provider closure.  Counters stay readable forever
        (scrape continuity across engine generations), but a stopped
        engine — and through ``queue_depth_fn`` its batcher, and
        through the batcher the model — must not be retained by the
        process-global registry for the rest of the process lifetime.
        Called by the engines' stop()/drain() finalization."""
        if self._released:
            return  # idempotent: a second stop() must not re-zero
        self._released = True
        fn = self.queue_depth_fn
        last = 0
        if fn is not None:
            try:
                last = int(fn())
            except Exception:  # noqa: BLE001 — provider already dead
                last = 0
        child = self._ctr["queue_depth"]
        child.set(last)
        child.set_fn(None)
        self.queue_depth_fn = None

    def unregister(self) -> None:
        """Remove this object's label series from the registry entirely
        (implies :meth:`release`).  Direct child references — including
        this object's own properties — keep working, but the series
        stop being rendered/summed: the fleet's bounded-retirement
        scheme folds an old engine generation's final counts into a
        static carry and then reclaims its series, so a week of hot
        swaps cannot grow registry memory or the /metrics payload
        without bound."""
        self.release()
        for key, fam in self._fams.items():
            fam.remove(**self._label_kv)

    def drop_stats(self) -> Tuple[float, int]:
        """Windowed (drop_rate, submitted) — drops are shed + rejected;
        the rate is over requests submitted in the window.  The
        engine's `degraded` health threshold reads this per dispatch,
        so it is O(1): running sums, trim only walks expired entries."""
        now = self.clock()
        with self._lock:
            self._trim(now)
            submitted, dropped = self._submit_n, self._drop_n
        return (dropped / submitted if submitted else 0.0), submitted

    # ---- reporting -----------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat rolling-window stats: ``qps`` (completed LOGICAL
        requests over the window — same population as the latency
        percentiles, so an oversize request split into chunks counts
        once), ``rows_per_sec`` (dispatched rows over the window),
        ``batch_occupancy`` (mean rows/bucket fill of dispatched
        batches — 1.0 means every dispatch ran a full bucket),
        ``queue_depth`` (LIVE when the engine wired ``queue_depth_fn``,
        else at the last dispatch), ``last_dispatch_age_s`` (stall
        gauge: None until the first dispatch), ``dispatch_ms`` (mean
        device dispatch+fetch wall time), nearest-rank latency
        percentiles in ms, the overload counters
        (``rejected``/``shed``/``expired``/``admission_blocked_ms``)
        and ``deadline_p99_ms`` (latency tail of deadlined requests).
        ``per_bucket`` breaks the dispatch wall times down by shape
        bucket (p50/p95/p99 + counts per bucket): a global mean hides
        which executables are slow, and the per-shape-bucket medians
        are exactly what the calibration harvest
        (``flexflow_tpu.search.calibration.harvest_serve_dispatch``)
        feeds back into the cost model."""
        now = self.clock()
        depth_fn = self.queue_depth_fn
        live_depth = depth_fn() if depth_fn is not None else None
        with self._lock:
            self._trim(now)
            disp = list(self._dispatches)
            lat_rows = list(self._latencies)
            lats = [l for _, l in lat_rows]
            dlats = [l for _, l in self._deadline_lats]
            depth = self._queue_depth if live_depth is None else live_depth
            last_t = self._last_dispatch_t
            totals = (self.total_dispatches, self.total_requests,
                      self.total_rows, self.total_errors,
                      self.total_rejected, self.total_shed,
                      self.total_expired, self.blocked_ms_total,
                      self.total_cancelled, self.total_submitted)
        span = self.window_s
        if disp:
            span = min(self.window_s, max(1e-6, now - disp[0][0]))
        req_span = self.window_s
        if lat_rows:
            req_span = min(self.window_s,
                           max(1e-6, now - lat_rows[0][0]))
        rows = sum(d[1] for d in disp)
        occ = (sum(d[1] / d[2] for d in disp) / len(disp)) if disp else 0.0
        q = quantiles(lats)
        qd = quantiles(dlats)

        def ms(v):
            # None, not NaN: json.dumps writes bare `NaN` (invalid
            # JSON) and would break the one-parseable-line contract
            # for any strict consumer when the latency window is empty
            return None if v != v else round(v * 1e3, 3)

        by_bucket: Dict[int, list] = {}
        for d in disp:
            by_bucket.setdefault(d[2], []).append(d)
        per_bucket = {}
        for b in sorted(by_bucket):
            rows_b = by_bucket[b]
            qb = quantiles([d[4] for d in rows_b])
            per_bucket[str(b)] = {
                "dispatches": len(rows_b),
                "rows": sum(d[1] for d in rows_b),
                "dispatch_p50_ms": ms(qb[0.5]),
                "dispatch_p95_ms": ms(qb[0.95]),
                "dispatch_p99_ms": ms(qb[0.99]),
            }

        return {
            "model": self.model_tag,
            "qps": round(len(lats) / req_span, 3),
            "rows_per_sec": round(rows / span, 3),
            "batch_occupancy": round(occ, 4),
            "queue_depth": depth,
            "last_dispatch_age_s": (None if last_t is None
                                    else round(now - last_t, 3)),
            "dispatch_ms": round(
                sum(d[4] for d in disp) / len(disp) * 1e3, 3) if disp
                else 0.0,
            "p50_ms": ms(q[0.5]),
            "p95_ms": ms(q[0.95]),
            "p99_ms": ms(q[0.99]),
            "deadline_p99_ms": ms(qd[0.99]),
            "per_bucket": per_bucket,
            "dispatches": totals[0],
            "requests": totals[1],
            "rows": totals[2],
            "errors": totals[3],
            "rejected": totals[4],
            "shed": totals[5],
            "expired": totals[6],
            "cancelled": totals[8],
            # offered-load lifetime total: submitted == requests +
            # rejected + shed + expired + errors + cancelled, the exact
            # reconciliation serve-bench (and the trace terminal-span
            # counts) pin
            "submitted": totals[9],
            "admission_blocked_ms": round(totals[7], 3),
        }

    def emit(self, extra: Dict | None = None) -> None:
        """One ``serve_stats`` JSON event line on the ``serve`` logger
        (fflogger.Category.event) — the serving analogue of fit()'s
        per-epoch event."""
        # eng rides as an event field (not in snapshot(): stats() is a
        # per-engine view already) so stream consumers — the cluster
        # router's load scrape — can attribute same-named tenants on
        # different hosts to the right engine generation
        get_logger("serve").event("serve_stats", eng=self.eng_id,
                                  **self.snapshot(), **(extra or {}))
