"""Serving observability: rolling QPS, batch occupancy, queue depth and
latency percentiles, emitted as JSON events through the existing
fflogger machinery (one ``serve_stats`` line per reporting interval —
the same one-parseable-line-per-record contract as fit()'s ``epoch``
events).

Quantiles come from :func:`flexflow_tpu.profiling.quantiles`
(nearest-rank — every reported p50/p95/p99 is a latency that actually
happened).  All state is windowed/bounded: a week-long serving process
must not grow its metrics memory with traffic.

Overload accounting (docs/serving.md "Overload, SLOs & degradation"):
``rejected`` / ``shed`` / ``expired`` lifetime counters classify every
load-management failure by its typed exception
(:mod:`flexflow_tpu.serving.errors`), ``admission_blocked_ms``
accumulates producer time spent blocked for admission, and
``deadline_p99_ms`` tracks the latency tail of the requests that
carried a deadline — the SLO-attainment gauge.  The windowed drop rate
(``drop_stats``) feeds the engine's ``degraded`` health transition.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple

from ..fflogger import get_logger
from ..profiling import quantiles
from .errors import DeadlineExceeded, GenerationCancelled, SheddedError


class ServingMetrics:
    """Thread-safe rolling serving statistics.

    Dispatch-side records (`record_dispatch`) come from the dispatcher
    thread, one per packed batch; request-side records
    (`record_request`) fire when a logical request's future resolves.
    `snapshot()` reduces the rolling window to the flat dict that both
    the ``serve_stats`` JSON event and serve-bench report.

    ``queue_depth_fn`` (settable after construction) makes the reported
    queue depth LIVE: without it, depth freezes at the last dispatch —
    a wedged dispatcher behind a growing queue would look healthy.  The
    engine points it at ``batcher.queue_depth``; ``last_dispatch_age_s``
    is the stall gauge's other half."""

    def __init__(self, window_s: float = 30.0, max_latency_samples: int = 4096,
                 clock: Callable[[], float] = time.monotonic,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 model: str = ""):
        self.window_s = float(window_s)
        self.clock = clock
        self.queue_depth_fn = queue_depth_fn
        # tenant identity: every snapshot/serve_stats row carries
        # ``model=<name>`` so two engines in one process (a model
        # fleet) emit distinguishable event streams —
        # calibration.harvest_serve_dispatch keys its dispatch entries
        # on it ("" = the pre-fleet single-engine default)
        self.model_tag = str(model)
        self._lock = threading.Lock()
        # every rolling-window structure and counter below is
        # guarded_by self._lock (RL009): records arrive from producer
        # threads AND the dispatcher concurrently
        # (t, rows, bucket, n_reqs, dispatch_s) per packed batch
        self._dispatches: deque = deque()  # guarded_by: self._lock
        # (t, latency_s) per completed logical request
        self._latencies: deque = deque(  # guarded_by: self._lock
            maxlen=max_latency_samples)
        # (t, latency_s) for the subset that carried a deadline — the
        # SLO-attainment population deadline_p99_ms reports on
        self._deadline_lats: deque = deque(  # guarded_by: self._lock
            maxlen=max_latency_samples)
        # (t, n) windowed admission/drop event streams for the health
        # state machine's shed-rate threshold, with RUNNING sums so
        # drop_stats() is O(1) on the hot dispatcher path; trimmed on
        # every append (not only on reads) and hard-capped so a wedged
        # dispatcher under a submit storm cannot grow metrics memory
        self._submit_ts: deque = deque()  # guarded_by: self._lock
        self._drop_ts: deque = deque()    # guarded_by: self._lock
        self._submit_n = 0   # guarded_by: self._lock
        self._drop_n = 0     # guarded_by: self._lock
        self._queue_depth = 0  # guarded_by: self._lock
        # the dispatcher's heartbeat: last dispatch completion time,
        # the stall gauge last_dispatch_age_s reads
        self._last_dispatch_t: Optional[float] = None  # guarded_by: self._lock
        self.total_dispatches = 0  # guarded_by: self._lock
        self.total_requests = 0    # guarded_by: self._lock
        self.total_rows = 0        # guarded_by: self._lock
        self.total_errors = 0      # guarded_by: self._lock
        self.total_rejected = 0    # guarded_by: self._lock
        self.total_shed = 0        # guarded_by: self._lock
        self.total_expired = 0     # guarded_by: self._lock
        self.total_cancelled = 0   # guarded_by: self._lock
        self.blocked_ms_total = 0.0  # guarded_by: self._lock

    # hard cap on windowed admission/drop EVENTS (not requests — each
    # entry may carry n>1): bounds memory even when the window itself
    # would hold more
    _MAX_WINDOW_EVENTS = 65536

    # ---- recording -----------------------------------------------------
    def _trim(self, now: float) -> None:  # guarded_by: self._lock
        horizon = now - self.window_s
        for dq in (self._dispatches, self._latencies, self._deadline_lats):
            while dq and dq[0][0] < horizon:
                dq.popleft()
        while self._submit_ts and (self._submit_ts[0][0] < horizon
                                   or len(self._submit_ts)
                                   > self._MAX_WINDOW_EVENTS):
            self._submit_n -= self._submit_ts.popleft()[1]
        while self._drop_ts and (self._drop_ts[0][0] < horizon
                                 or len(self._drop_ts)
                                 > self._MAX_WINDOW_EVENTS):
            self._drop_n -= self._drop_ts.popleft()[1]

    def record_dispatch(self, rows: int, bucket: int, n_reqs: int,
                        queue_depth: int, dispatch_s: float) -> None:
        now = self.clock()
        with self._lock:
            self._dispatches.append((now, rows, bucket, n_reqs, dispatch_s))
            self._queue_depth = queue_depth
            self._last_dispatch_t = now
            self.total_dispatches += 1
            self.total_rows += rows
            self._trim(now)

    def record_request(self, latency_s: float,
                       deadlined: bool = False) -> None:
        now = self.clock()
        with self._lock:
            self._latencies.append((now, latency_s))
            if deadlined:
                self._deadline_lats.append((now, latency_s))
            self.total_requests += 1

    def record_submitted(self, n: int = 1) -> None:
        """Offered-load denominator for the windowed drop rate: one per
        LOGICAL request entering submit(), admitted or not."""
        now = self.clock()
        with self._lock:
            self._submit_ts.append((now, int(n)))
            self._submit_n += int(n)
            self._trim(now)

    def record_rejected(self, n: int = 1) -> None:
        """Requests refused at admission (OverloadError from submit —
        they never queued, so no future carries the failure)."""
        now = self.clock()
        with self._lock:
            self.total_rejected += int(n)
            self._drop_ts.append((now, int(n)))
            self._drop_n += int(n)
            self._trim(now)

    def record_blocked(self, seconds: float) -> None:
        """Producer time spent blocked for admission (`block` policy) —
        invisible in latency percentiles (the request had not been
        submitted yet) but very visible to the caller."""
        with self._lock:
            self.blocked_ms_total += float(seconds) * 1e3

    def record_failure(self, exc: BaseException) -> None:
        """ONE classification point for every exception that resolves a
        LOGICAL request's future: expiry and shedding are load
        management (their own counters, and sheds feed the windowed
        drop rate), anything else is a dispatch error.  Split chunks
        count their request once — the caller only invokes this for the
        completion that actually resolved the future, so the population
        matches every other per-request metric."""
        now = self.clock()
        with self._lock:
            if isinstance(exc, DeadlineExceeded):
                self.total_expired += 1
            elif isinstance(exc, SheddedError):
                self.total_shed += 1
                self._drop_ts.append((now, 1))
                self._drop_n += 1
                self._trim(now)
            elif isinstance(exc, GenerationCancelled):
                # a client (or the serve_cancel_at_token fault) ended
                # the stream — NOT a dispatch failure; counting it as
                # one would make a healthy engine whose clients cancel
                # look like it is throwing errors
                self.total_cancelled += 1
            else:
                self.total_errors += 1

    def drop_stats(self) -> Tuple[float, int]:
        """Windowed (drop_rate, submitted) — drops are shed + rejected;
        the rate is over requests submitted in the window.  The
        engine's `degraded` health threshold reads this per dispatch,
        so it is O(1): running sums, trim only walks expired entries."""
        now = self.clock()
        with self._lock:
            self._trim(now)
            submitted, dropped = self._submit_n, self._drop_n
        return (dropped / submitted if submitted else 0.0), submitted

    # ---- reporting -----------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat rolling-window stats: ``qps`` (completed LOGICAL
        requests over the window — same population as the latency
        percentiles, so an oversize request split into chunks counts
        once), ``rows_per_sec`` (dispatched rows over the window),
        ``batch_occupancy`` (mean rows/bucket fill of dispatched
        batches — 1.0 means every dispatch ran a full bucket),
        ``queue_depth`` (LIVE when the engine wired ``queue_depth_fn``,
        else at the last dispatch), ``last_dispatch_age_s`` (stall
        gauge: None until the first dispatch), ``dispatch_ms`` (mean
        device dispatch+fetch wall time), nearest-rank latency
        percentiles in ms, the overload counters
        (``rejected``/``shed``/``expired``/``admission_blocked_ms``)
        and ``deadline_p99_ms`` (latency tail of deadlined requests).
        ``per_bucket`` breaks the dispatch wall times down by shape
        bucket (p50/p95/p99 + counts per bucket): a global mean hides
        which executables are slow, and the per-shape-bucket medians
        are exactly what the calibration harvest
        (``flexflow_tpu.search.calibration.harvest_serve_dispatch``)
        feeds back into the cost model."""
        now = self.clock()
        depth_fn = self.queue_depth_fn
        live_depth = depth_fn() if depth_fn is not None else None
        with self._lock:
            self._trim(now)
            disp = list(self._dispatches)
            lat_rows = list(self._latencies)
            lats = [l for _, l in lat_rows]
            dlats = [l for _, l in self._deadline_lats]
            depth = self._queue_depth if live_depth is None else live_depth
            last_t = self._last_dispatch_t
            totals = (self.total_dispatches, self.total_requests,
                      self.total_rows, self.total_errors,
                      self.total_rejected, self.total_shed,
                      self.total_expired, self.blocked_ms_total,
                      self.total_cancelled)
        span = self.window_s
        if disp:
            span = min(self.window_s, max(1e-6, now - disp[0][0]))
        req_span = self.window_s
        if lat_rows:
            req_span = min(self.window_s,
                           max(1e-6, now - lat_rows[0][0]))
        rows = sum(d[1] for d in disp)
        occ = (sum(d[1] / d[2] for d in disp) / len(disp)) if disp else 0.0
        q = quantiles(lats)
        qd = quantiles(dlats)

        def ms(v):
            # None, not NaN: json.dumps writes bare `NaN` (invalid
            # JSON) and would break the one-parseable-line contract
            # for any strict consumer when the latency window is empty
            return None if v != v else round(v * 1e3, 3)

        by_bucket: Dict[int, list] = {}
        for d in disp:
            by_bucket.setdefault(d[2], []).append(d)
        per_bucket = {}
        for b in sorted(by_bucket):
            rows_b = by_bucket[b]
            qb = quantiles([d[4] for d in rows_b])
            per_bucket[str(b)] = {
                "dispatches": len(rows_b),
                "rows": sum(d[1] for d in rows_b),
                "dispatch_p50_ms": ms(qb[0.5]),
                "dispatch_p95_ms": ms(qb[0.95]),
                "dispatch_p99_ms": ms(qb[0.99]),
            }

        return {
            "model": self.model_tag,
            "qps": round(len(lats) / req_span, 3),
            "rows_per_sec": round(rows / span, 3),
            "batch_occupancy": round(occ, 4),
            "queue_depth": depth,
            "last_dispatch_age_s": (None if last_t is None
                                    else round(now - last_t, 3)),
            "dispatch_ms": round(
                sum(d[4] for d in disp) / len(disp) * 1e3, 3) if disp
                else 0.0,
            "p50_ms": ms(q[0.5]),
            "p95_ms": ms(q[0.95]),
            "p99_ms": ms(q[0.99]),
            "deadline_p99_ms": ms(qd[0.99]),
            "per_bucket": per_bucket,
            "dispatches": totals[0],
            "requests": totals[1],
            "rows": totals[2],
            "errors": totals[3],
            "rejected": totals[4],
            "shed": totals[5],
            "expired": totals[6],
            "cancelled": totals[8],
            "admission_blocked_ms": round(totals[7], 3),
        }

    def emit(self, extra: Dict | None = None) -> None:
        """One ``serve_stats`` JSON event line on the ``serve`` logger
        (fflogger.Category.event) — the serving analogue of fit()'s
        per-epoch event."""
        get_logger("serve").event("serve_stats", **self.snapshot(),
                                  **(extra or {}))
