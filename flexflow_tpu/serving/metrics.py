"""Serving observability: rolling QPS, batch occupancy, queue depth and
latency percentiles, emitted as JSON events through the existing
fflogger machinery (one ``serve_stats`` line per reporting interval —
the same one-parseable-line-per-record contract as fit()'s ``epoch``
events).

Quantiles come from :func:`flexflow_tpu.profiling.quantiles`
(nearest-rank — every reported p50/p95/p99 is a latency that actually
happened).  All state is windowed/bounded: a week-long serving process
must not grow its metrics memory with traffic.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict

from ..fflogger import get_logger
from ..profiling import quantiles


class ServingMetrics:
    """Thread-safe rolling serving statistics.

    Dispatch-side records (`record_dispatch`) come from the dispatcher
    thread, one per packed batch; request-side records
    (`record_request`) fire when a logical request's future resolves.
    `snapshot()` reduces the rolling window to the flat dict that both
    the ``serve_stats`` JSON event and serve-bench report."""

    def __init__(self, window_s: float = 30.0, max_latency_samples: int = 4096,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.clock = clock
        self._lock = threading.Lock()
        # (t, rows, bucket, n_reqs, dispatch_s) per packed batch
        self._dispatches: deque = deque()
        # (t, latency_s) per completed logical request
        self._latencies: deque = deque(maxlen=max_latency_samples)
        self._queue_depth = 0
        self.total_dispatches = 0
        self.total_requests = 0
        self.total_rows = 0
        self.total_errors = 0

    # ---- recording -----------------------------------------------------
    def _trim(self, now: float) -> None:
        horizon = now - self.window_s
        while self._dispatches and self._dispatches[0][0] < horizon:
            self._dispatches.popleft()
        while self._latencies and self._latencies[0][0] < horizon:
            self._latencies.popleft()

    def record_dispatch(self, rows: int, bucket: int, n_reqs: int,
                        queue_depth: int, dispatch_s: float) -> None:
        now = self.clock()
        with self._lock:
            self._dispatches.append((now, rows, bucket, n_reqs, dispatch_s))
            self._queue_depth = queue_depth
            self.total_dispatches += 1
            self.total_rows += rows
            self._trim(now)

    def record_request(self, latency_s: float) -> None:
        now = self.clock()
        with self._lock:
            self._latencies.append((now, latency_s))
            self.total_requests += 1

    def record_errors(self, n_reqs: int) -> None:
        """LOGICAL requests failed by the dispatch error path (split
        chunks count their request once, like every other metric) —
        without this a failure storm would read as an IDLE engine in
        serve_stats (no dispatches, no requests) while clients get
        exceptions."""
        with self._lock:
            self.total_errors += int(n_reqs)

    # ---- reporting -----------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat rolling-window stats: ``qps`` (completed LOGICAL
        requests over the window — same population as the latency
        percentiles, so an oversize request split into chunks counts
        once), ``rows_per_sec`` (dispatched rows over the window),
        ``batch_occupancy`` (mean rows/bucket fill of dispatched
        batches — 1.0 means every dispatch ran a full bucket),
        ``queue_depth`` (at the last dispatch), ``dispatch_ms`` (mean
        device dispatch+fetch wall time) and nearest-rank latency
        percentiles in ms.  ``per_bucket`` breaks the dispatch wall
        times down by shape bucket (p50/p95/p99 + counts per bucket):
        a global mean hides which executables are slow, and the
        per-shape-bucket medians are exactly what the calibration
        harvest (``flexflow_tpu.search.calibration
        .harvest_serve_dispatch``) feeds back into the cost model."""
        now = self.clock()
        with self._lock:
            self._trim(now)
            disp = list(self._dispatches)
            lat_rows = list(self._latencies)
            lats = [l for _, l in lat_rows]
            depth = self._queue_depth
            totals = (self.total_dispatches, self.total_requests,
                      self.total_rows, self.total_errors)
        span = self.window_s
        if disp:
            span = min(self.window_s, max(1e-6, now - disp[0][0]))
        req_span = self.window_s
        if lat_rows:
            req_span = min(self.window_s,
                           max(1e-6, now - lat_rows[0][0]))
        rows = sum(d[1] for d in disp)
        occ = (sum(d[1] / d[2] for d in disp) / len(disp)) if disp else 0.0
        q = quantiles(lats)

        def ms(v):
            # None, not NaN: json.dumps writes bare `NaN` (invalid
            # JSON) and would break the one-parseable-line contract
            # for any strict consumer when the latency window is empty
            return None if v != v else round(v * 1e3, 3)

        by_bucket: Dict[int, list] = {}
        for d in disp:
            by_bucket.setdefault(d[2], []).append(d)
        per_bucket = {}
        for b in sorted(by_bucket):
            rows_b = by_bucket[b]
            qb = quantiles([d[4] for d in rows_b])
            per_bucket[str(b)] = {
                "dispatches": len(rows_b),
                "rows": sum(d[1] for d in rows_b),
                "dispatch_p50_ms": ms(qb[0.5]),
                "dispatch_p95_ms": ms(qb[0.95]),
                "dispatch_p99_ms": ms(qb[0.99]),
            }

        return {
            "qps": round(len(lats) / req_span, 3),
            "rows_per_sec": round(rows / span, 3),
            "batch_occupancy": round(occ, 4),
            "queue_depth": depth,
            "dispatch_ms": round(
                sum(d[4] for d in disp) / len(disp) * 1e3, 3) if disp
                else 0.0,
            "p50_ms": ms(q[0.5]),
            "p95_ms": ms(q[0.95]),
            "p99_ms": ms(q[0.99]),
            "per_bucket": per_bucket,
            "dispatches": totals[0],
            "requests": totals[1],
            "rows": totals[2],
            "errors": totals[3],
        }

    def emit(self, extra: Dict | None = None) -> None:
        """One ``serve_stats`` JSON event line on the ``serve`` logger
        (fflogger.Category.event) — the serving analogue of fit()'s
        per-epoch event."""
        get_logger("serve").event("serve_stats", **self.snapshot(),
                                  **(extra or {}))
