"""Typed serving failures (docs/serving.md "Overload, SLOs &
degradation").

Overload is a handled regime, not an accident: when the engine cannot
serve a request it fails FAST with one of these types so a client can
distinguish "retry elsewhere / back off" (admission) from "the answer
arrived too late to matter" (deadline) and react per class.  All three
derive from :class:`ServingError` so ``except ServingError`` catches
exactly the engine's load-management failures and nothing else — a
dispatch bug (device error, shape mismatch) still surfaces as whatever
it was.

Deliberately dependency-free: the batcher raises/injects these without
importing the engine or metrics.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base class for load-management failures of the serving engine."""


class OverloadError(ServingError):
    """Request refused at admission: the bounded queue was full under
    the ``reject`` policy (or could not be made to fit under
    ``shed_oldest``), or the engine is draining.  Raised synchronously
    from ``submit()`` — the request never entered the queue."""


class SheddedError(ServingError):
    """A QUEUED request was evicted to make room for newer work
    (``shed_oldest`` admission) or failed by ``drain(timeout)`` as a
    straggler.  Delivered through the request's future."""


class DeadlineExceeded(ServingError):
    """The request's ``deadline_ms`` passed while it was still queued;
    the batcher expired it BEFORE packing, so no device dispatch was
    burned on an answer nobody is waiting for.  Delivered through the
    request's future."""


class KVCacheExhausted(SheddedError):
    """The paged KV pool ran out of pages for a stream — even after
    LRU-evicting every unreferenced prefix-cache page — so the stream
    was shed to protect the others (docs/serving.md "Paged KV & prefix
    caching").  A ``SheddedError`` subclass: pool exhaustion is a
    load-shedding decision, counted and traced as ``shed``.  Only
    reachable when ``serve_kv_pages`` undersizes the pool below the
    dense worst case (the auto default cannot exhaust)."""


class GenerationCancelled(ServingError):
    """A token-generation stream was cancelled — by its client
    (``GenerationStream.cancel()``) or the ``serve_cancel_at_token``
    fault — while decoding.  The stream's KV slot is freed immediately
    and ONLY this stream fails; tokens already streamed remain valid.
    Delivered through the stream's future (docs/serving.md "Token
    generation")."""
