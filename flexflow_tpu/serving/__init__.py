"""flexflow_tpu.serving — the inference-serving subsystem
(docs/serving.md): shape-bucketed AOT executables + a dynamic
micro-batcher over a compiled FFModel, with rolling serving metrics and
the ``flexflow-tpu serve-bench`` harness."""

from .batcher import (MicroBatcher, Request, bucket_for, derive_buckets,
                      split_sizes)
from .engine import ServingEngine
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "MicroBatcher", "Request", "ServingMetrics",
           "bucket_for", "derive_buckets", "split_sizes"]
