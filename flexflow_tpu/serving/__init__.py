"""flexflow_tpu.serving — the inference-serving subsystem
(docs/serving.md): shape-bucketed AOT executables + a dynamic
micro-batcher over a compiled FFModel, with admission control,
per-request deadlines/priorities, engine health states, rolling
serving metrics and the ``flexflow-tpu serve-bench`` harness."""

from .batcher import (ADMISSION_POLICIES, MicroBatcher, Request, bucket_for,
                      derive_buckets, split_sizes)
from .engine import HEALTH_STATES, ServingEngine
from .errors import (DeadlineExceeded, GenerationCancelled,
                     KVCacheExhausted, OverloadError, ServingError,
                     SheddedError)
from .fleet import FleetEngine, ModelRegistry, TenantSpec
from .generation import GenerationEngine, GenerationStream
from .metrics import ServingMetrics

__all__ = ["ServingEngine", "MicroBatcher", "Request", "ServingMetrics",
           "ServingError", "OverloadError", "SheddedError",
           "DeadlineExceeded", "GenerationCancelled", "KVCacheExhausted",
           "GenerationEngine",
           "GenerationStream", "FleetEngine", "ModelRegistry",
           "TenantSpec", "ADMISSION_POLICIES", "HEALTH_STATES",
           "bucket_for", "derive_buckets", "split_sizes"]
