"""serve-bench --disagg — disaggregated prefill/decode vs co-located
chunked prefill on an adversarial prefill-heavy trace (ISSUE 19).

The scenario chunked prefill (PR 15) can only SOFTEN: one long-decode
victim stream is mid-generation when a flood of long-prompt/short-
decode requests arrives.  Co-located, every prefill chunk burns a
decode-step boundary, so the victim's inter-token gaps stretch by one
chunk dispatch per turn.  Disaggregated, the flood prefills on a
prefill-role host while the victim decodes on a decode-role host that
dispatches nothing but decode steps.

Methodology — calibrated replay over real-engine runs:

1. **Wall arms** (recorded under ``wall``): every arm runs for real —
   ``colo chunk=C`` per requested chunk size, ``colo chunk=0``
   (monolithic, informational), and ``disagg`` (a
   :class:`~.router.FleetRouter` over one prefill-role and one
   decode-role fleet; every stream prefills on ``pf0`` and its KV page
   chain migrates to ``dc0``).  These pin the correctness half of the
   acceptance: cross-engine ``submitted == terminals`` reconciliation,
   every stream migrated, and the REAL per-migration costs
   (export / handoff / import, measured in situ).  Their latency rows
   are informational: in CI the two "hosts" are forced host-platform
   devices sharing ONE core, so cross-arm wall-clock deltas measure
   the OS scheduler, not the serving architecture.
2. **Calibration**: solo op costs measured on the real engines —
   decode step, chunk op per size, monolithic prefill per flood
   prompt — plus the measured migration costs from (1).
3. **Replay** (the primary ``colo``/``disagg`` rows): each arm's
   dispatch discipline composed deterministically on the calibrated
   price list, each host on its own timeline — what the engines do on
   a two-host topology.  Colo: per boundary, at most one prefill
   chunk (Sarathi) then the batch decode step — the victim pays
   ``chunk_op + decode_step`` per gap while the flood prefills.
   Disagg: the prefill host runs nothing but FIFO monolithic prefills
   (a dedicated host needs no chunking); the decode host's boundaries
   cost ``decode_step``, plus the measured import once per adoption —
   the victim's worst gap is ``decode_step + import``, and
   ``import << chunk_op`` is the whole point.  This is the calibrated
   cost-model discipline the router's design leans on (PAPERS.md
   [2008.01040]): the same price list that keeps routing honest
   across device kinds scores the architectures.

Per arm: victim inter-token gap percentiles + max stall, flood TTFT
percentiles, and TTFT-SLO goodput (tokens of flood requests whose
TTFT met the SLO, per second; SLO defaults to the best chunked-colo
arm's median flood TTFT).  A separate parity leg pins colo vs disagg
tokens BIT-identical under greedy sampling with the prefix cache on
AND off (real engines, real migration).  Wall arms keep their
min-over-repeats run on the max-gap statistic (PR 15 convention).

Every payload stamps ``device_kind``, ``calibration_digest`` and
``comm_plan_digest`` (PR 7/PR 9 conventions) and
``estimator: calibrated-replay``.  Artifact:
``artifacts/disagg_bench_r19.json`` (gated by
``scripts/check_gen_artifacts.py``).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..generation.bench import VOCAB, _build_lm, _pctl


def make_flood_trace(n: int, prompt_lo: int, prompt_hi: int,
                     seed: int) -> List[np.ndarray]:
    """The adversarial flood: long prompts (prefill-heavy), decoded
    only a couple of tokens each — pure prefill pressure."""
    rng = np.random.default_rng(seed)
    return [rng.integers(1, VOCAB,
                         int(rng.integers(prompt_lo, prompt_hi + 1))
                         ).astype(np.int32)
            for _ in range(n)]


def _victim_prompt() -> np.ndarray:
    return np.arange(1, 5, dtype=np.int32)


_WARM_TOKENS = 4


def _interference_once(submit: Callable, floods: List[np.ndarray],
                       victim_new: int, flood_new: int
                       ) -> Tuple[List[float], List[float], float]:
    """One interference run: start the victim, let it decode a few
    warm-up tokens (past prefill AND — disaggregated — past its own
    one-time migration handover, which is a per-stream cost, not
    steady-state interference), release the flood, and keep only the
    victim inter-token gaps that OVERLAP the flood window.  Returns
    ``(window_gaps_s, flood_ttfts_s, flood_elapsed_s)``."""
    times: List[float] = []
    tick = threading.Event()
    victim = submit(_victim_prompt(), max_new_tokens=victim_new)

    def consume():
        for _ in victim:
            times.append(time.perf_counter())
            tick.set()

    th = threading.Thread(target=consume, daemon=True,
                          name="ff-disagg-bench-victim")
    th.start()
    deadline = time.perf_counter() + 60
    while len(times) < _WARM_TOKENS and time.perf_counter() < deadline:
        tick.wait(timeout=1.0)
        tick.clear()
    t0 = time.perf_counter()
    streams = [submit(p, max_new_tokens=flood_new) for p in floods]
    for s in streams:
        s.result(timeout=600)
    t1 = time.perf_counter()
    victim.result(timeout=600)
    th.join(timeout=60)
    ttfts = [s.ttft for s in streams if s.ttft is not None]
    gaps = [b - a for a, b in zip(times, times[1:])
            if b > t0 and a < t1]
    return gaps, ttfts, t1 - t0


def _reconciled(snaps: List[Dict]) -> bool:
    """submitted == sum of terminals, SUMMED across the engines — a
    migrated stream submits on one engine and terminates on another,
    so only the cross-engine sum balances."""
    submitted = sum(s["submitted"] for s in snaps)
    terminal = sum(s["requests"] + s["rejected"] + s["shed"]
                   + s["expired"] + s["errors"] + s["cancelled"]
                   for s in snaps)
    return submitted == terminal


def _mk_run(gaps: List[float], ttfts: List[float], dt: float,
            snaps: List[Dict], flood_new: int) -> Dict:
    return {
        "victim_max_gap_ms": round(max(gaps) * 1e3, 3) if gaps else None,
        "victim_tpot": _pctl(gaps),
        "flood_ttft": _pctl(ttfts),
        "flood_elapsed_s": round(dt, 4),
        "reconciliation_ok": _reconciled(snaps),
        "_ttfts": ttfts,          # raw, for SLO goodput; dropped later
        "_flood_new": flood_new,
    }


def _goodput(run: Dict, slo_ms: float) -> float:
    met = sum(1 for t in run["_ttfts"] if t * 1e3 <= slo_ms)
    return round(met * run["_flood_new"] / run["flood_elapsed_s"], 2)


def _keep_best(best: Optional[Dict], run: Dict) -> Dict:
    """min-over-repeats on the max-gap statistic (noise floor)."""
    if best is None or (run["victim_max_gap_ms"] or 1e9) < \
            (best["victim_max_gap_ms"] or 1e9):
        return run
    return best


def calibrate(model, slots: int, max_seq: int,
              chunk_sizes: Tuple[int, ...],
              floods: List[np.ndarray]) -> Dict:
    """Measured solo op costs on the real engines — the per-op price
    list the replay composes.  Medians; runs after the wall arms, so
    every program is compile-cache warm."""
    from ..generation.engine import GenerationEngine

    def _eng(chunk):
        return GenerationEngine(model, slots=slots, max_seq=max_seq,
                                stats_every=0, prefill_chunk=chunk,
                                prefix_cache="off")

    cal: Dict = {}
    with _eng(0) as eng:
        times: List[float] = []
        for _ in eng.submit(_victim_prompt(), max_new_tokens=33):
            times.append(time.perf_counter())
        gaps = sorted(b - a for a, b in zip(times, times[1:]))
        cal["decode_step_ms"] = round(gaps[len(gaps) // 2] * 1e3, 4)
        eng.submit(floods[0], max_new_tokens=1).result(timeout=600)
        mono = []
        for p in floods:
            s = eng.submit(p, max_new_tokens=1)
            s.result(timeout=600)
            mono.append(round(s.ttft * 1e3, 4))
        cal["mono_prefill_ms"] = mono
    cal["chunk_op_ms"] = {}
    for c in chunk_sizes:
        with _eng(c) as eng:
            eng.submit(floods[0], max_new_tokens=1).result(timeout=600)
            vals = []
            for p in floods[:3]:
                s = eng.submit(p, max_new_tokens=1)
                s.result(timeout=600)
                vals.append(s.ttft * 1e3 / -(-len(p) // c))
            vals.sort()
            cal["chunk_op_ms"][str(c)] = round(vals[len(vals) // 2], 4)
    return cal


def _mk_replay(gaps_s: List[float], ttfts_s: List[float],
               elapsed_s: float, flood_new: int, chunk: int) -> Dict:
    return {
        "victim_max_gap_ms": round(max(gaps_s) * 1e3, 3),
        "victim_tpot": _pctl(gaps_s),
        "flood_ttft": _pctl(ttfts_s),
        "flood_elapsed_s": round(elapsed_s, 4),
        "prefill_chunk": chunk,
        "_ttfts": list(ttfts_s),
        "_flood_new": flood_new,
    }


def _replay_colo(cal: Dict, lengths: List[int], chunk: int,
                 flood_new: int) -> Dict:
    """Deterministic replay of the co-located discipline: per dispatch
    boundary, at most ONE prefill chunk (Sarathi) then the decode step
    for the active batch.  The victim emits at every boundary; a flood
    stream's first token lands at its final chunk's boundary."""
    cd = cal["decode_step_ms"] / 1e3
    if chunk > 0:
        cc = cal["chunk_op_ms"][str(chunk)] / 1e3
        work = [[cc] * -(-length // chunk) for length in lengths]
    else:
        work = [[ms / 1e3] for ms in cal["mono_prefill_ms"]]
    n = len(lengths)
    ttft: List[float] = [0.0] * n
    finish: List[float] = [0.0] * n
    left = [flood_new] * n
    active: List[int] = []
    t, vt, i = 0.0, [0.0], 0
    while any(x > 0 for x in left):
        just = None
        if i < n:
            t += work[i].pop(0)
            if not work[i]:
                just, i = i, i + 1
        t += cd
        vt.append(t)
        for j in list(active):
            left[j] -= 1
            if left[j] == 0:
                finish[j] = t
                active.remove(j)
        if just is not None:
            ttft[just] = t
            left[just] -= 1
            if left[just] == 0:
                finish[just] = t
            else:
                active.append(just)
    elapsed = max(finish)
    gaps = [b - a for a, b in zip(vt, vt[1:])]
    return _mk_replay(gaps, ttft, elapsed, flood_new, chunk)


def _replay_disagg(cal: Dict, lengths: List[int],
                   flood_new: int) -> Dict:
    """Deterministic replay of the disaggregated discipline, each host
    on its own timeline.  Prefill host: nothing but FIFO monolithic
    prefills (a dedicated prefill host needs no chunking); a stream's
    first token is sampled at its prefill completion there, then the
    chain ships (measured export + handoff cost) and waits for the
    decode host.  Decode host: a boundary every decode step; ONE
    adoption per boundary (the engine contract), charged the measured
    import cost — the victim's worst gap is decode + import."""
    cd = cal["decode_step_ms"] / 1e3
    ship = (cal["migrate_export_ms"] + cal["migrate_handoff_ms"]) / 1e3
    imp = cal["migrate_import_ms"] / 1e3
    n = len(lengths)
    done: List[float] = []
    acc = 0.0
    for ms in cal["mono_prefill_ms"]:
        acc += ms / 1e3
        done.append(acc)
    ttft = list(done)
    ready = [d + ship for d in done]
    pending = list(range(n))          # FIFO == ready order
    left = [flood_new - 1] * n
    finish = list(done)               # overwritten when decode moves
    active: List[int] = []
    t, vt = 0.0, [0.0]
    while pending or active:
        joined = None
        if pending and ready[pending[0]] <= t:
            joined = pending.pop(0)
            t += imp
        t += cd
        vt.append(t)
        for j in list(active):
            left[j] -= 1
            if left[j] == 0:
                finish[j] = t
                active.remove(j)
        if joined is not None:
            if left[joined] <= 0:
                finish[joined] = t
            else:
                left[joined] -= 1
                if left[joined] == 0:
                    finish[joined] = t
                else:
                    active.append(joined)
    elapsed = max(finish)
    gaps = [b - a for a, b in zip(vt, vt[1:])]
    return _mk_replay(gaps, ttft, elapsed, flood_new, 0)


def run_colo_arm(model, slots: int, max_seq: int, chunk: int,
                 floods: List[np.ndarray], victim_new: int,
                 flood_new: int, repeats: int) -> Dict:
    from ..generation.engine import GenerationEngine

    best = None
    for _ in range(repeats):
        eng = GenerationEngine(model, slots=slots, max_seq=max_seq,
                               stats_every=0, prefill_chunk=chunk,
                               prefix_cache="off")
        with eng:
            gaps, ttfts, dt = _interference_once(
                eng.submit, floods, victim_new, flood_new)
            snap = eng.stats()
        run = _mk_run(gaps, ttfts, dt, [snap], flood_new)
        run["engine_tpot_p95_ms"] = snap["tpot_p95_ms"]
        best = _keep_best(best, run)
    best["prefill_chunk"] = chunk
    return best


def build_disagg(model, slots: int, max_seq: int, chunk: int,
                 prefix_cache: str = "off", pf_pace_s: float = 0.002):
    """One prefill-role + one decode-role fleet over shared weights,
    fronted by a router.  The decode engine is PINNED to a second jax
    device when one exists (``--xla_force_host_platform_device_count``
    gives single-host CPU runs one) — without its own device the
    decode host's steps would queue behind prefill programs on the
    shared executor, which is exactly the interference disaggregation
    removes.  Returns (router, fleets, engines); the caller stops the
    router first, then the fleets."""
    import jax

    from ..fleet import FleetEngine
    from ..generation.engine import GenerationEngine
    from .router import FleetRouter

    devs = jax.devices()
    dc_dev = devs[1] if len(devs) > 1 else None
    pf_eng = GenerationEngine(model, slots=slots, max_seq=max_seq,
                              stats_every=0, prefill_chunk=chunk,
                              prefix_cache=prefix_cache)
    dc_eng = GenerationEngine(model, slots=slots, max_seq=max_seq,
                              stats_every=0, prefix_cache=prefix_cache,
                              device=dc_dev)
    # prefill-host pacing (FleetEngine.pace_s): on a shared substrate
    # the prefill role hands the core to the decode host at every op
    # boundary — TTFT cost ~pace_s per chunk, decode-tail win ~a whole
    # scheduler quantum per collision
    pf = FleetEngine(pace_s=pf_pace_s)
    dc = FleetEngine()
    pf.add_engine("lm", pf_eng)
    dc.add_engine("lm", dc_eng)
    pf.start()
    dc.start()
    router = FleetRouter()
    router.add_host("pf0", pf, role="prefill")
    router.add_host("dc0", dc, role="decode")
    router.start()
    return router, (pf, dc), (pf_eng, dc_eng)


def run_disagg_arm(model, slots: int, max_seq: int, pf_chunk: int,
                   floods: List[np.ndarray], victim_new: int,
                   flood_new: int, repeats: int) -> Dict:
    best = None
    for _ in range(repeats):
        router, fleets, (pf_eng, dc_eng) = build_disagg(
            model, slots, max_seq, pf_chunk)
        try:
            gaps, ttfts, dt = _interference_once(
                lambda p, **kw: router.submit("lm", p, **kw),
                floods, victim_new, flood_new)
            snaps = [pf_eng.stats(), dc_eng.stats()]
            rstats = router.stats()
        finally:
            router.stop()
            for f in fleets:
                f.stop()
        run = _mk_run(gaps, ttfts, dt, snaps, flood_new)
        run["engine_tpot_p95_ms"] = snaps[1]["tpot_p95_ms"]
        run["migrations"] = rstats["migrations"]
        run["migrated_bytes"] = rstats["migrated_bytes"]
        run["routes"] = rstats["routes"]
        run["all_migrated"] = (
            rstats["migrations"] == len(floods) + 1)
        # the REAL per-migration costs, measured in situ — the replay
        # charges these (sorted: medians taken downstream)
        run["_mig_export_ms"] = sorted(pf_eng.migrate_export_ms)
        run["_mig_import_ms"] = sorted(dc_eng.migrate_import_ms)
        run["_mig_handoff_ms"] = (rstats["migrate_ms_total"]
                                  / max(1, rstats["migrations"]))
        best = _keep_best(best, run)
    # the disaggregated prefill host needs no chunking to protect
    # anyone — decode isolation comes from PLACEMENT — so monolithic
    # prefill (pf_chunk=0) is correct on real multi-chip hardware.
    # When both "hosts" share one physical core (forced host-platform
    # devices), a coarse chunk still pays: the longest prefill program
    # bounds the OS-timeslice collision window for decode threads.
    best["prefill_chunk"] = pf_chunk
    return best


def run_parity(model, slots: int, max_seq: int, chunk: int,
               n_prompts: int, max_new: int, seed: int) -> Dict:
    """Greedy colo vs disagg token parity, prefix cache on AND off.
    Bit-identical is the contract: migration moves the KV pages, it
    must never perturb a single logit."""
    from ..generation.engine import GenerationEngine

    rng = np.random.default_rng(seed + 7)
    prompts = [rng.integers(1, VOCAB,
                            int(rng.integers(4, max_seq // 2))
                            ).astype(np.int32)
               for _ in range(n_prompts)]
    out = {"prompts": n_prompts, "max_new": max_new}
    for pc in ("on", "off"):
        eng = GenerationEngine(model, slots=slots, max_seq=max_seq,
                               stats_every=0, prefill_chunk=chunk,
                               prefix_cache=pc)
        with eng:
            colo = [list(int(t) for t in
                         eng.submit(p, max_new_tokens=max_new)
                         .result(timeout=600))
                    for p in prompts]
        router, fleets, _ = build_disagg(model, slots, max_seq, chunk,
                                         prefix_cache=pc)
        try:
            disagg = [list(int(t) for t in
                           router.submit("lm", p, max_new_tokens=max_new)
                           .result(timeout=600))
                      for p in prompts]
        finally:
            router.stop()
            for f in fleets:
                f.stop()
        out[f"prefix_{pc}"] = (colo == disagg)
    return out


def run_disagg_bench(requests: int = 6, prompt_lo: int = 192,
                     prompt_hi: int = 224, flood_new: int = 2,
                     victim_new: int = 64, slots: int = 8,
                     max_seq: int = 256, d_model: int = 256,
                     num_heads: int = 4, num_layers: int = 2,
                     seed: int = 0, chunks: Tuple[int, ...] = (16, 32),
                     pf_chunk: int = 32,
                     repeats: int = 2, parity_prompts: int = 6,
                     parity_new: int = 8, slo_ms: float = 0.0,
                     calibration_digest=None) -> Dict:
    import jax

    from ...analysis import comm_plan_digest_for_model
    from ...search.calibration import device_kind as _device_kind

    model = _build_lm(slots, max_seq, d_model, num_heads, num_layers,
                      seed)
    dk = _device_kind()
    stamp = {"device_kind": dk, "calibration_digest": calibration_digest,
             "comm_plan_digest": comm_plan_digest_for_model(model)}
    floods = make_flood_trace(requests, prompt_lo, prompt_hi, seed)

    # ---- wall arms: real engines, real migrations (correctness +
    # in-situ migration costs; latency informational — see module
    # docstring).  A max-gap statistic is hostage to GIL hand-off
    # latency, so tighten the switch interval for every arm equally.
    prev_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    try:
        wall_colo: Dict[str, Dict] = {}
        for chunk in (0,) + tuple(chunks):
            wall_colo[f"chunk{chunk}"] = run_colo_arm(
                model, slots, max_seq, chunk, floods, victim_new,
                flood_new, repeats)
        wall_disagg = run_disagg_arm(model, slots, max_seq, pf_chunk,
                                     floods, victim_new, flood_new,
                                     repeats)
    finally:
        sys.setswitchinterval(prev_switch)

    # ---- calibration: solo op prices + the measured migration costs
    cal = calibrate(model, slots, max_seq, tuple(chunks), floods)

    def _med(xs):
        return xs[len(xs) // 2] if xs else 0.0

    cal["migrate_export_ms"] = round(
        _med(wall_disagg.pop("_mig_export_ms")), 4)
    cal["migrate_import_ms"] = round(
        _med(wall_disagg.pop("_mig_import_ms")), 4)
    cal["migrate_handoff_ms"] = round(
        wall_disagg.pop("_mig_handoff_ms"), 4)

    # ---- deterministic replay (the primary rows)
    lengths = [len(p) for p in floods]
    colo: Dict[str, Dict] = {}
    for chunk in (0,) + tuple(chunks):
        row = _replay_colo(cal, lengths, chunk, flood_new)
        row["reconciliation_ok"] = \
            wall_colo[f"chunk{chunk}"]["reconciliation_ok"]
        colo[f"chunk{chunk}"] = row
    disagg = _replay_disagg(cal, lengths, flood_new)
    for k in ("reconciliation_ok", "engine_tpot_p95_ms", "migrations",
              "migrated_bytes", "routes", "all_migrated"):
        disagg[k] = wall_disagg[k]

    chunked = [colo[f"chunk{c}"] for c in chunks]
    if slo_ms <= 0:
        # the SLO every arm is scored against: the best chunked-colo
        # arm's median flood TTFT — colo meets it about half the time
        # by construction, so goodput deltas are about ROUTING, not
        # about a generously slack (or impossibly tight) target
        slo_ms = min(r["flood_ttft"]["p50_ms"] for r in chunked
                     if r["flood_ttft"]["p50_ms"] is not None)
    for row in list(colo.values()) + [disagg]:
        row["goodput_toks_per_s"] = _goodput(row, slo_ms)
        row.pop("_ttfts", None)
        row.pop("_flood_new", None)
        row.update(stamp)
    for row in list(wall_colo.values()) + [wall_disagg]:
        row["goodput_toks_per_s"] = _goodput(row, slo_ms)
        row.pop("_ttfts", None)
        row.pop("_flood_new", None)

    parity = run_parity(model, slots, max_seq, chunks[0],
                        parity_prompts, parity_new, seed)

    # the comparison the tentpole claims: strictly better decode-path
    # latency than the best co-located chunked-prefill arm AT
    # EQUAL-OR-BETTER TTFT-SLO GOODPUT.  A colo arm buys a gentle
    # stall by shrinking its chunk — and pays for it in goodput — so
    # the stall/TPOT baseline is the best-stall arm among the arms
    # that match disagg's goodput; when no chunked arm reaches it
    # (the usual case), the closest goodput competitor is the
    # baseline.  Latency here is what the victim OBSERVES (inter-
    # token gap): engine-side step walls can't see a decode step that
    # never dispatched.
    best_goodput = max(r["goodput_toks_per_s"] for r in chunked)
    qualified = [r for r in chunked
                 if r["goodput_toks_per_s"]
                 >= disagg["goodput_toks_per_s"]]
    pool = qualified or [max(chunked,
                             key=lambda r: r["goodput_toks_per_s"])]
    baseline = min(pool, key=lambda r: r["victim_max_gap_ms"])
    acceptance = {
        "baseline_arm": f"chunk{baseline['prefill_chunk']}",
        "tpot_p95_better":
            disagg["victim_tpot"]["p95_ms"]
            < baseline["victim_tpot"]["p95_ms"],
        "victim_stall_better":
            disagg["victim_max_gap_ms"]
            < baseline["victim_max_gap_ms"],
        "goodput_no_worse":
            disagg["goodput_toks_per_s"] >= best_goodput,
        "tokens_bit_identical":
            bool(parity["prefix_on"] and parity["prefix_off"]),
        "reconciliation_ok": all(
            r["reconciliation_ok"]
            for r in list(colo.values()) + [disagg]),
        "all_migrated": bool(disagg["all_migrated"]),
    }
    return {
        "bench": "disagg",
        "backend": jax.default_backend(),
        "num_devices": len(jax.devices()),
        "estimator": "calibrated-replay",
        **stamp,
        "calibration": cal,
        "wall": {"colo": wall_colo, "disagg": wall_disagg},
        "config": {
            "requests": requests, "prompt_lo": prompt_lo,
            "prompt_hi": prompt_hi, "flood_new": flood_new,
            "victim_new": victim_new, "slots": slots,
            "max_seq": max_seq, "d_model": d_model,
            "num_heads": num_heads, "num_layers": num_layers,
            "seed": seed, "chunks": list(chunks),
            "pf_chunk": pf_chunk, "repeats": repeats,
            "slo_ms": round(float(slo_ms), 3),
        },
        "colo": colo,
        "disagg": disagg,
        "parity": parity,
        "acceptance": acceptance,
    }


def main(argv: Optional[List[str]] = None) -> None:
    import os

    # the decode host needs its own executor (see build_disagg): ask
    # the CPU platform for a second device BEFORE the backend
    # initializes — a no-op if the caller already set the flag or the
    # backend is already up (the bench then runs single-device and
    # records num_devices accordingly)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()

    from ...fflogger import silenced

    ap = argparse.ArgumentParser(
        prog="flexflow-tpu serve-bench --disagg",
        description="disaggregated prefill/decode vs co-located "
                    "chunked prefill (adversarial prefill-heavy trace)")
    ap.add_argument("--requests", type=int, default=6,
                    help="flood size (long-prompt/short-decode)")
    ap.add_argument("--prompt-lo", type=int, default=192)
    ap.add_argument("--prompt-hi", type=int, default=224)
    ap.add_argument("--flood-new", type=int, default=2)
    ap.add_argument("--victim-new", type=int, default=64,
                    help="victim stream's decode budget")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--chunks", type=str, default="16,32",
                    help="comma-separated colo prefill chunk sizes")
    ap.add_argument("--pf-chunk", type=int, default=32,
                    help="disagg prefill-host chunk (0 = monolithic)")
    ap.add_argument("--repeats", type=int, default=2)
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="TTFT SLO; 0 = best chunked-colo median")
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args(argv)

    chunks = tuple(int(c) for c in args.chunks.split(",") if c)
    with silenced("ff", "serve"):
        payload = run_disagg_bench(
            requests=args.requests, prompt_lo=args.prompt_lo,
            prompt_hi=args.prompt_hi, flood_new=args.flood_new,
            victim_new=args.victim_new, slots=args.slots,
            max_seq=args.max_seq, d_model=args.d_model,
            num_heads=args.num_heads, num_layers=args.layers,
            seed=args.seed, chunks=chunks, pf_chunk=args.pf_chunk,
            repeats=args.repeats, slo_ms=args.slo_ms)
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
