"""flexflow_tpu.serving.cluster — disaggregated prefill/decode serving
(docs/serving.md "Disaggregated prefill/decode").

* :class:`FleetRouter` — a fleet-of-fleets front: requests route to a
  prefill host picked from scraped ``gen_stats``/``fleet_stats`` load
  signals, and at prefill completion the KV page chain migrates
  (``pages.export_pages``/``import_pages``) to a decode-role host, so
  decode engines dispatch nothing but decode steps.
"""

from .router import FleetRouter

__all__ = ["FleetRouter"]
