"""FleetRouter — disaggregated prefill/decode serving across N hosts
(docs/serving.md "Disaggregated prefill/decode"; ISSUE 19 tentpole).

Chunked prefill (PR 15) only *bounds* prefill–decode interference:
every prefill chunk still burns a decode-step boundary on the engine
hosting it.  The router removes the interference class instead of
rationing it — a DistServe/Splitwise-style split over the pieces the
stack already has:

* each **host** is one started :class:`~..fleet.FleetEngine` (its own
  dispatcher thread — in-process here, one-per-host in the elastic
  world), tagged ``prefill`` | ``decode`` | ``mixed``
  (:data:`~..fleet.registry.TENANT_ROLES`);
* ``submit(model, prompt)`` routes to the least-loaded healthy
  prefill/mixed host.  Load is scraped off the observability stream —
  the router taps :mod:`~...fflogger` and keys the freshest
  ``gen_stats``/``serve_stats`` record by its ``eng`` field, falling
  back to a live queue-depth read before a tenant's first emission —
  so routing needs no side channel into the engines;
* generation submissions carry a **handoff**: at prefill completion
  the source engine exports the stream's KV page chain (ONE
  ``device_get`` — ``pages.export_pages``) and offers it here; the
  router picks the best decode-role host at THAT instant and enqueues
  the payload on its tenant engine (``adopt_migrated`` — imported with
  one ``device_put`` on the destination's own dispatch thread).  True
  = the stream decodes on an engine that dispatches *nothing but*
  decode steps; False/raise = the source keeps decoding co-located,
  one ``serve_health`` fallback event, NO stream fails;
* ``mark_down(host)`` (or the ``route_host_down:<name>`` FF_FAULT)
  drains the downed host's queued requests to survivors
  (``fail_pending`` → ``requeue`` — admitted work is never re-judged),
  lets in-flight streams finish where they run, and excludes the host
  from every future route/migration.  ``migrate_fail_at:N`` makes the
  Nth migration handoff raise deterministically (fires once) — the
  fallback contract above is exactly what the fault matrix pins.

Observability: one ``route`` span per submitted (sampled) stream —
span counts reconcile with request terminals exactly — plus the
``ff_router_*`` registry families (migrations by status, migrated
bytes, per-role queue depth) and ``router_*`` lifecycle events.

``clock`` is injectable (RL008); the router owns no threads — every
host's fleet dispatcher does the work, the router only fronts them.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from ... import faults, fflogger
from ...fflogger import get_logger
from ...obs import lockwatch
from ..fleet.engine import FleetEngine
from ..fleet.registry import TENANT_ROLES


class _Host:
    """Router-side state of one fleet host."""

    __slots__ = ("name", "fleet", "role", "down")

    def __init__(self, name: str, fleet: FleetEngine, role: str):
        self.name = name
        self.fleet = fleet
        self.role = role
        self.down = False


class FleetRouter:
    """Route requests across role-tagged fleet hosts, migrating KV
    pages from prefill to decode engines at prefill completion.

    ::

        router = FleetRouter()
        router.add_host("pf0", prefill_fleet, role="prefill")
        router.add_host("dc0", decode_fleet, role="decode")
        with router:                       # installs the stats tap
            stream = router.submit("chat", prompt_ids)
            for tok in stream:
                ...

    The router never starts or stops the fleets — hosts arrive started
    and outlive the router (``stop()`` only detaches the scrape tap).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._lock = lockwatch.lock("FleetRouter._lock")
        self._hosts: Dict[str, _Host] = {}  # guarded_by: self._lock
        self._started = False               # guarded_by: self._lock
        # freshest gen_stats/serve_stats record per engine generation
        # (eng id): written by the fflogger tap thread(s), read by
        # routing — whole-record replacement, so no lock is needed
        # (CPython dict item assignment is atomic)
        self._scrape: Dict[str, Dict] = {}
        self._n_routes = 0                  # guarded_by: self._lock
        self._n_migrations = 0              # guarded_by: self._lock
        self._migrated_bytes = 0            # guarded_by: self._lock
        # FF_FAULT state (faults.router_faults, materialized at
        # start()): the Nth migration handoff raises; a named host is
        # marked down at the first routing decision.  Both fire once.
        self._fault_migrate_n: Optional[int] = None
        self._fault_down_host: Optional[str] = None
        self._migrate_attempts = 0          # guarded_by: self._lock
        self._migrate_ms_total = 0.0        # guarded_by: self._lock
        self._fault_fired = {"migrate": False,
                             "down": False}  # guarded_by: self._lock
        from ...obs.registry import get_registry
        from ..metrics import next_engine_id
        reg = get_registry()
        self._eng = next_engine_id()
        self._c_migrations = reg.counter(
            "ff_router_migrations_total",
            "KV page-chain migrations by outcome "
            "(ok/declined/error)", ("eng", "status"))
        self._c_bytes = reg.counter(
            "ff_router_migrated_bytes_total",
            "Host bytes of KV pages shipped prefill -> decode",
            ("eng",)).labels(eng=self._eng)
        self._g_depth = reg.gauge(
            "ff_router_queue_depth",
            "Summed tenant queue depth per host role", ("eng", "role"))

    # ---- lifecycle -----------------------------------------------------
    def add_host(self, name: str, fleet: FleetEngine,
                 role: str = "mixed") -> None:
        """Attach one STARTED fleet as a routable host."""
        if role not in TENANT_ROLES:
            raise ValueError(f"host {name!r}: role must be one of "
                             f"{TENANT_ROLES}, got {role!r}")
        with self._lock:
            if name in self._hosts:
                raise ValueError(f"duplicate host {name!r}")
            self._hosts[name] = _Host(name, fleet, role)

    def start(self) -> "FleetRouter":
        with self._lock:
            if self._started:
                return self
            self._started = True
            hosts = {h.name: h.role for h in self._hosts.values()}
        for spec in faults.router_faults():
            if spec.kind == "migrate_fail_at":
                self._fault_migrate_n = int(spec.arg)
            elif spec.kind == "route_host_down":
                self._fault_down_host = str(spec.arg)
        fflogger.add_tap(self._tap)
        get_logger("serve").event("router_start", hosts=hosts)
        return self

    def stop(self) -> None:
        with self._lock:
            if not self._started:
                return
            self._started = False
            routes, migs = self._n_routes, self._n_migrations
        fflogger.remove_tap(self._tap)
        get_logger("serve").event("router_stop", routes=routes,
                                  migrations=migs)

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- load scrape ---------------------------------------------------
    def _tap(self, rec: Dict) -> None:
        if rec.get("event") not in ("gen_stats", "serve_stats"):
            return
        eng = str(rec.get("eng", ""))
        if eng:
            self._scrape[eng] = rec

    def _score(self, host: _Host, model: str) -> Optional[float]:
        """Load of ``model``'s tenant on ``host`` (lower = better), or
        None when the host does not serve the model.  The scraped
        stats record (keyed by the tenant engine's ``eng`` id) is the
        primary signal; the live queue depth floors it so a burst
        between emissions is never invisible, and active decode slots
        count toward load so a slot-full decode host yields to an
        emptier one."""
        try:
            t = host.fleet._tenant(model)
        except KeyError:
            return None
        eng = t.engine
        depth = float(eng._batcher.queue_depth)
        rec = self._scrape.get(str(getattr(eng.metrics, "eng_id", "")))
        if rec is not None:
            depth = max(depth, float(rec.get("queue_depth") or 0.0))
        slots = getattr(eng, "_slots_state", None)
        active = (sum(1 for s in slots if s is not None)
                  if slots is not None else 0)
        return depth + active

    def _pick(self, model: str, roles, exclude: str = ""
              ) -> Optional[_Host]:
        with self._lock:
            hosts = [h for h in self._hosts.values()
                     if not h.down and h.name != exclude]
        best, best_score = None, None
        for role in roles:  # earlier role wins ties across tiers
            for h in sorted((h for h in hosts if h.role == role),
                            key=lambda h: h.name):
                s = self._score(h, model)
                if s is None:
                    continue
                if best_score is None or s < best_score:
                    best, best_score = h, s
            if best is not None:
                return best
        return best

    # ---- routing -------------------------------------------------------
    def submit(self, model: str, *args, **kw):
        """Route one request for tenant ``model``: generation prompts
        return a GenerationStream (carrying the migration handoff when
        a decode target exists), dense rows a Future."""
        self._maybe_fire_host_down()
        src = self._pick(model, ("prefill", "mixed"))
        if src is None:
            with self._lock:
                have = sorted(self._hosts)
            raise KeyError(
                f"no healthy prefill/mixed host serves {model!r} "
                f"(hosts: {have})")
        t0 = self.clock()
        tenant = src.fleet._tenant(model)
        if (tenant.kind == "generation"
                and self._pick(model, ("decode", "mixed"),
                               exclude=src.name) is not None):
            kw.setdefault("handoff",
                          self._make_handoff(model, src.name))
        out = src.fleet.submit(model, *args, **kw)
        with self._lock:
            self._n_routes += 1
        self._route_span(tenant.engine, out, src, model, t0)
        self._update_depth_gauges()
        return out

    def _route_span(self, engine, out, src: _Host, model: str,
                    t0: float) -> None:
        """One ``route`` span per sampled stream — the routing leg of
        the request timeline, so span counts reconcile with the
        engines' terminal ``request`` spans exactly."""
        tracer = getattr(engine, "_tracer", None)
        trace = getattr(out, "trace", None)
        if tracer is None or not tracer.active or trace is None:
            return
        tracer.span("route", trace, t0, self.clock(), tid="router",
                    host=src.name, role=src.role, model=model)

    def _make_handoff(self, model: str, src_name: str) -> Callable:
        def handoff(payload: Dict) -> bool:
            with self._lock:
                self._migrate_attempts += 1
                attempt = self._migrate_attempts
                fire = (self._fault_migrate_n is not None
                        and attempt == self._fault_migrate_n
                        and not self._fault_fired["migrate"])
                if fire:
                    self._fault_fired["migrate"] = True
            if fire:
                raise RuntimeError(
                    f"FF_FAULT: injected migration failure at "
                    f"attempt {attempt}")
            h0 = time.perf_counter()
            dst = self._pick(model, ("decode", "mixed"),
                             exclude=src_name)
            if dst is None:
                self._c_migrations.labels(
                    eng=self._eng, status="declined").inc()
                return False
            try:
                tenant = dst.fleet._tenant(model)
                dev = getattr(tenant.engine, "device", None)
                if dev is not None:
                    # push the page bytes onto the DESTINATION device
                    # from here (the source engine's dispatcher — a
                    # throughput thread): the decode host's import
                    # then only scatters resident rows, so adoption
                    # never stalls its decode cadence on a transfer
                    import jax
                    payload = dict(payload,
                                   pages=jax.device_put(
                                       payload["pages"], dev))
                adopted = bool(tenant.engine.adopt_migrated(payload))
            except BaseException:
                self._c_migrations.labels(
                    eng=self._eng, status="error").inc()
                raise
            if not adopted:
                self._c_migrations.labels(
                    eng=self._eng, status="declined").inc()
                return False
            dst.fleet._wake.set()
            with self._lock:
                self._n_migrations += 1
                self._migrated_bytes += int(payload.get("nbytes", 0))
                self._migrate_ms_total += (time.perf_counter()
                                           - h0) * 1e3
            self._c_migrations.labels(eng=self._eng,
                                      status="ok").inc()
            self._c_bytes.inc(int(payload.get("nbytes", 0)))
            return True

        return handoff

    # ---- health --------------------------------------------------------
    def _maybe_fire_host_down(self) -> None:
        with self._lock:
            name = self._fault_down_host
            fire = (name is not None and name in self._hosts
                    and not self._fault_fired["down"])
            if fire:
                self._fault_fired["down"] = True
        if fire:
            self.mark_down(name)

    def mark_down(self, name: str) -> Dict[str, int]:
        """Mark one host down: no new routes or migrations target it,
        its tenants' QUEUED requests drain to surviving hosts (requeue
        — admitted work is never re-judged, zero streams fail), and
        in-flight work finishes where it runs (the host's own
        dispatcher keeps stepping it).  Returns ``{model: moved}``."""
        with self._lock:
            host = self._hosts.get(name)
            if host is None:
                raise KeyError(f"no host {name!r}")
            host.down = True
        moved: Dict[str, int] = {}
        for model in host.fleet.names():
            try:
                tenant = host.fleet._tenant(model)
            except KeyError:
                continue  # unloaded while we walked
            reqs = tenant.engine._batcher.fail_pending()
            if not reqs:
                continue
            dst = self._pick(model, ("prefill", "mixed", "decode"))
            if dst is None:
                # nowhere to drain to: give the queue back — the
                # downed host still serves what it already admitted
                tenant.engine._batcher.requeue(reqs)
                continue
            dst.fleet._tenant(model).engine._batcher.requeue(reqs)
            dst.fleet._wake.set()
            moved[model] = len(reqs)
        host.fleet._wake.set()
        get_logger("serve").event("router_host_down", host=name,
                                  moved=moved)
        return moved

    # ---- reporting -----------------------------------------------------
    def _update_depth_gauges(self) -> None:
        with self._lock:
            hosts = list(self._hosts.values())
        depth_by_role = {r: 0.0 for r in TENANT_ROLES}
        for h in hosts:
            for model in h.fleet.names():
                try:
                    t = h.fleet._tenant(model)
                except KeyError:
                    continue
                depth_by_role[h.role] += t.engine._batcher.queue_depth
        for role, d in depth_by_role.items():
            self._g_depth.labels(eng=self._eng, role=role).set(d)

    def stats(self) -> Dict:
        with self._lock:
            hosts = list(self._hosts.values())
            out = {
                "routes": self._n_routes,
                "migrations": self._n_migrations,
                "migrated_bytes": self._migrated_bytes,
                "migrate_attempts": self._migrate_attempts,
                "migrate_ms_total": round(self._migrate_ms_total, 3),
            }
        out["hosts"] = {
            h.name: {"role": h.role, "down": h.down,
                     "models": h.fleet.names()}
            for h in hosts}
        return out


__all__ = ["FleetRouter"]
