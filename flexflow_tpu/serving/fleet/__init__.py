"""flexflow_tpu.serving.fleet — multi-tenant serving: N models, one
mesh (docs/serving.md "Model fleets").

* :class:`ModelRegistry` / :class:`TenantSpec` — name → checkpoint +
  searched strategy + engine kind + fairness/admission knobs
  (JSON file or programmatic);
* :class:`FleetEngine` — one dispatcher multiplexing every resident
  engine under weighted-fair device-time scheduling, with hot
  load/unload/swap at dispatch boundaries;
* :func:`fleet_gate_report` — the device-free co-residency gate
  (``flexflow-tpu lint --fleet``): does the fleet FIT on the HBM?
"""

from .autoscale import TenantAutoscaler
from .engine import FleetEngine
from .gate import fleet_gate_report, model_residency, static_params_bytes
from .registry import (ENGINE_KINDS, TENANT_ROLES, ModelRegistry,
                       TenantSpec, build_model, builtin_builders,
                       validate_fleet_json)

__all__ = ["FleetEngine", "ModelRegistry", "TenantSpec",
           "TenantAutoscaler",
           "fleet_gate_report", "model_residency", "static_params_bytes",
           "validate_fleet_json", "builtin_builders", "build_model",
           "ENGINE_KINDS", "TENANT_ROLES"]
