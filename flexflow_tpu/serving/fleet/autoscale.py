"""Per-tenant autoscaling policy (ISSUE 19 satellite; the PR 12
remainder) — grow a loaded tenant's weighted-fair share from its
rolling queue-depth window, release it after the burst.

The policy is deliberately a pure observer: the fleet dispatcher feeds
it ``(tenant, queue_depth, current_weight, now)`` samples at its own
boundary and applies whatever new weight the policy returns
(``fleet_autoscale`` event per change).  It never touches engines or
locks — all state is per-tenant deques on the INJECTED clock, so a
fake-clock test drives the whole grow/decay cycle deterministically
(RL008; tests/test_cluster.py).

Semantics:

* each tenant's samples older than ``window_s`` are dropped; the mean
  depth over the surviving window is the load signal (a single spike
  does not retrigger growth, a drained queue does not instantly decay);
* mean depth >= ``high_depth`` → weight grows by ``grow`` (capped at
  ``max_weight`` x the tenant's BASE weight — the weight it had when
  first observed, so an operator-set 2.0 share scales around 2.0, not
  around the fleet default);
* mean depth <= ``low_depth`` → weight decays by the same factor back
  toward (never below) the base — idling releases borrowed share at
  the same rate it was granted;
* decisions are paced at ``every_s`` per tenant so one burst yields a
  bounded ramp, not a weight explosion within a single window.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, Optional, Tuple


class TenantAutoscaler:
    """Rolling-window weight policy for :class:`~.engine.FleetEngine`
    (pass as its ``autoscaler=``).  See the module docstring for the
    grow/decay semantics."""

    def __init__(self, window_s: float = 5.0, every_s: float = 1.0,
                 high_depth: float = 4.0, low_depth: float = 0.5,
                 grow: float = 1.5, max_scale: float = 8.0):
        if window_s <= 0 or every_s <= 0:
            raise ValueError("window_s/every_s must be > 0")
        if grow <= 1.0:
            raise ValueError(f"grow must be > 1.0, got {grow}")
        if max_scale < 1.0:
            raise ValueError(f"max_scale must be >= 1.0, got {max_scale}")
        if low_depth >= high_depth:
            raise ValueError("low_depth must be < high_depth")
        self.window_s = float(window_s)
        self.every_s = float(every_s)
        self.high_depth = float(high_depth)
        self.low_depth = float(low_depth)
        self.grow = float(grow)
        self.max_scale = float(max_scale)
        # per-tenant: (samples deque of (t, depth), base weight,
        # last decision time)
        self._win: Dict[str, Deque[Tuple[float, float]]] = {}
        self._base: Dict[str, float] = {}
        self._last: Dict[str, float] = {}

    def observe(self, name: str, depth: float, weight: float,
                now: float) -> Optional[float]:
        """Record one queue-depth sample; return the new weight when
        the policy wants a change, else None.  Called by the fleet
        dispatcher only — single-threaded by construction."""
        base = self._base.setdefault(name, float(weight))
        win = self._win.setdefault(name, deque())
        win.append((now, float(depth)))
        while win and win[0][0] < now - self.window_s:
            win.popleft()
        if now - self._last.get(name, -1e30) < self.every_s:
            return None
        mean = sum(d for _, d in win) / len(win)
        new = None
        if mean >= self.high_depth:
            new = min(base * self.max_scale, weight * self.grow)
        elif mean <= self.low_depth and weight > base:
            new = max(base, weight / self.grow)
        if new is None or abs(new - weight) < 1e-12:
            return None
        self._last[name] = now
        return new

    def forget(self, name: str) -> None:
        """Drop a departed tenant's window/base (unload path)."""
        self._win.pop(name, None)
        self._base.pop(name, None)
        self._last.pop(name, None)


__all__ = ["TenantAutoscaler"]
