"""FleetEngine — N models, ONE mesh, one dispatcher (docs/serving.md
"Model fleets").

The single-model stack (ServingEngine, GenerationEngine) gives each
model its own dispatcher thread; co-residing N of them that way shares
the device by luck — whichever thread wins the GIL/device next.  The
fleet engine makes sharing a POLICY: every resident engine runs in
fleet mode (``begin_external_dispatch`` — producer side unchanged:
PR 8's bounded-queue admission, deadlines, priorities per model) and
ONE fleet dispatcher thread interleaves their packed dispatches under
**weighted-fair device-time scheduling**:

* each tenant accrues virtual time ``used_device_seconds / weight``;
  the dispatcher always serves the backlogged tenant with the LOWEST
  virtual time (start-time fair queuing: a tenant returning from idle
  is clamped to the minimum active virtual time, so idling never banks
  credit);
* an optional per-tenant ``qps_rows`` budget (token bucket on the
  injectable clock) caps a tenant's throughput even when the device is
  otherwise free;
* isolation is therefore by construction: tenant A offered 2x its
  capacity can saturate only ITS queue (bounded, shed_oldest) and its
  weight-share of device time — tenant B's goodput is preserved
  (``serve-bench --fleet`` pins >= 90% of solo).

**Hot load / unload / swap**: ``load()`` builds + compiles + warms the
new model's executables on a BACKGROUND thread (the expensive part —
serving never stalls), then enqueues an atomic publish that the
dispatcher applies at a dispatch boundary.  A swap (same name) moves
the outgoing engine's pending queue onto the replacement
(``MicroBatcher.requeue`` — admitted work is never re-judged), so an
in-flight request spans the swap without failing; ``unload()`` closes
admission, flushes the queue through the normal dispatch path, and
fails only past-deadline stragglers (``drain`` semantics).

The ``fleet_load_fail:<name>`` / ``fleet_swap_at_dispatch:N`` FF_FAULT
kinds (flexflow_tpu.faults) make load failures and swap timing
deterministic under test.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ... import faults
from ...fflogger import get_logger
from ...obs import lockwatch
from ..engine import ServingEngine
from ..generation.engine import GenerationEngine
from .registry import ModelRegistry, TenantSpec, build_model


# counter continuity across hot swaps: the lifetime keys summed over
# every engine generation that served under one tenant name
_CONTINUITY_KEYS = ("dispatches", "requests", "rows", "errors",
                    "rejected", "shed", "expired", "cancelled",
                    "submitted")
# LIVE retired-generation metrics kept per tenant before the oldest is
# folded into the static carry and its registry series reclaimed: a
# generation this many swaps old has drained its transferred requests
# (each swap's moved queue resolves within the NEXT generation's
# serving period), so the fold is exact in practice while a week of
# hot swaps stays bounded in registry memory and /metrics payload
_MAX_RETIRED_METRICS = 4


class _Tenant:
    """Dispatcher-side state of one resident model."""

    __slots__ = ("name", "kind", "engine", "weight", "qps_rows", "vtime",
                 "allowance", "last_refill", "idle", "retired", "carried")

    def __init__(self, name: str, kind: str, engine, weight: float,
                 qps_rows: float, now: float):
        self.name = name
        self.kind = kind            # "dense" | "generation"
        self.engine = engine
        self.weight = float(weight)
        self.qps_rows = float(qps_rows)
        self.vtime = 0.0            # used device seconds / weight
        self.allowance = qps_rows   # token bucket (rows; 1s burst)
        self.last_refill = now
        self.idle = True            # for the SFQ idle clamp (_pick)
        # ServingMetrics of swapped-out engine generations.  LIVE
        # objects, not snapshots: a request transferred across the
        # swap resolves on the NEW engine but records into the metrics
        # its submit() closure captured — the OLD one — so counter
        # continuity needs the object, not a copy taken at swap time.
        # Bounded: beyond _MAX_RETIRED_METRICS generations the oldest
        # is folded into `carried` (static sums) and unregistered.
        self.retired: List = []
        self.carried: Dict[str, float] = {}

    def has_pending(self) -> bool:
        return self.engine.has_pending

    def refill(self, now: float) -> None:
        if self.qps_rows <= 0:
            return
        self.allowance = min(
            self.qps_rows,
            self.allowance + (now - self.last_refill) * self.qps_rows)
        self.last_refill = now

    def within_budget(self) -> bool:
        # eligible while the bucket is positive (it may go negative by
        # up to one dispatch and recover at qps_rows/s — standard
        # token-bucket overshoot).  NOT `>= 1.0`: the bucket is capped
        # at qps_rows, so a sub-1.0 budget would never reach 1 and the
        # tenant would be starved forever instead of paced
        return self.qps_rows <= 0 or self.allowance > 0.0

    @staticmethod
    def _dev0_param_bytes(model) -> int:
        total = 0
        dev0 = None
        for arr in model._params.values():
            shards = getattr(arr, "addressable_shards", None)
            if shards is None:
                total += arr.nbytes
                continue
            if dev0 is None:
                dev0 = min((s.device for s in shards),
                           key=lambda d: getattr(d, "id", 0))
            for s in shards:
                if s.device == dev0:
                    total += s.data.nbytes
        return total

    def resident_bytes(self) -> float:
        """The tenant's REAL always-resident per-device bytes: the
        device-0 shard bytes of every parameter, plus the generation
        engine's preallocated KV cache — and, under speculative
        decoding, the co-hosted draft model's params + its own KV page
        pool.  This is the number the static co-residency gate
        predicts byte-for-byte (fleet/gate.model_residency, pinned in
        tests/test_fleet.py)."""
        total = self._dev0_param_bytes(self.engine.model)
        if self.kind == "generation":
            total += self.engine.kv_cache_bytes
            draft = getattr(self.engine, "draft_model", None)
            if draft is not None:
                total += self._dev0_param_bytes(draft)
                total += self.engine.draft_kv_cache_bytes
        return float(total)


class FleetEngine:
    """Multi-tenant serving over one mesh.

    ::

        fleet = FleetEngine(registry)        # or FleetEngine()
        with fleet:                          # builds + starts tenants
            fut = fleet.submit("ranker", x_rows)
            stream = fleet.submit("chat", prompt_ids)
            fleet.load("ranker", wait=True)  # hot swap (new checkpoint)
            fleet.unload("chat", timeout=1.0)

    Tenants come from a :class:`~.registry.ModelRegistry` (built
    lazily at ``start()``) and/or are attached live via
    :meth:`add_engine` (an already-constructed engine) or :meth:`load`
    (background build + atomic publish).  ``clock``/``sleep`` are
    injectable for deterministic tests (RL008)."""

    # dispatcher park time between polls when nothing is due: short
    # enough to honor ~ms deadlines, long enough not to spin
    _IDLE_WAIT_S = 0.002

    def __init__(self, registry: Optional[ModelRegistry] = None,
                 mesh=None, stats_every_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 autoscaler=None, share_identical: bool = False,
                 pace_s: float = 0.0):
        self.registry = registry
        self.mesh = mesh
        self.clock = clock
        self._sleep = sleep
        self.stats_every_s = float(stats_every_s)
        # dispatch pacing (ISSUE 19): yield the CPU for pace_s after
        # every served dispatch.  A placement-aware knob for THROUGHPUT
        # roles sharing a substrate with a latency role — a paced
        # prefill host hands the core to a co-resident decode host at
        # every op boundary instead of once per scheduler quantum, for
        # a TTFT cost of pace_s per chunk (~1% of a long prefill).
        # Meaningless co-located: there the prefill chunk and the
        # decode step share ONE dispatch loop, so a pause here delays
        # the victim it would protect.  Zero = off (default).
        self.pace_s = float(pace_s)
        # per-tenant autoscaling policy (fleet/autoscale.py): consulted
        # by the dispatcher at its boundary with each tenant's queue
        # depth; a returned weight is applied under the lock and
        # announced as a fleet_autoscale event
        self.autoscaler = autoscaler
        # cross-tenant dispatch sharing (ISSUE 19 satellite): tenants
        # whose models share exec_digest() (two checkpoints of one
        # graph — same compiled programs, different params) are served
        # back-to-back in ONE dispatcher turn, so the second rides the
        # warm executables the first just ran.  Bit-parity vs separate
        # turns is pinned in tests (the digest guarantees the same
        # programs; only the params differ).
        self.share_identical = bool(share_identical)
        self._lock = lockwatch.lock("FleetEngine._lock")
        self._tenants: Dict[str, _Tenant] = {}  # guarded_by: self._lock
        # swapped-out GENERATION tenants still holding active decode
        # slots: the dispatcher keeps stepping them (admission closed,
        # queue already transferred) until every stream retires, then
        # finalizes — a swap must not strand or shed mid-flight
        # streams, whose KV state cannot move to the new engine
        self._retiring: List[_Tenant] = []  # guarded_by: self._lock
        # publish queue: (name, _Tenant) applied atomically at a
        # dispatch boundary by the dispatcher
        self._publishes: List = []   # guarded_by: self._lock
        self._thread: Optional[  # guarded_by: self._lock
            threading.Thread] = None
        self._stopped = False    # guarded_by: self._lock
        self._draining = False   # guarded_by: self._lock
        self._wake = threading.Event()
        # name of the tenant whose dispatch is currently executing
        # (dispatcher writes; unload() polls it so "queue drained"
        # includes the batch already popped into the in-flight
        # dispatch — benign read race, it only extends the wait)
        self._in_flight: Optional[str] = None  # dispatcher-thread-only
        self._n_dispatch = 0     # dispatcher-thread-only (single writer)
        self._last_stats_t = 0.0  # dispatcher-thread-only
        # SFQ global virtual clock: the vtime of the tenant served
        # LAST (~= the minimum among backlogged tenants) — a tenant
        # waking from idle is clamped UP to it so idling never banks
        # device-time credit.  Deliberately NOT a running max: a max
        # would include the waking tenant's own past position, forcing
        # it to wait for the flooding tenant to catch up to a
        # historical high-water before being served at all (measured:
        # the isolation sweep's tenant B lost ~13% of its SLO window
        # to exactly that)
        self._vclock = 0.0       # dispatcher-thread-only
        self._swap_hold = self._swap_hold_n()
        # observability plane: per-tenant fairness gauges + the fleet
        # dispatch counter live in the obs.registry (what fleet_stats
        # events report and /metrics exposes), and the flight-recorder
        # taps are installed so a fleet post-mortem covers every tenant
        from ...obs.flight import get_flight
        from ...obs.registry import get_registry
        from ..metrics import next_engine_id
        get_flight()
        reg = get_registry()
        # eng label = this fleet's own generation id (same sequence as
        # the per-engine metrics): two FleetEngines in one process —
        # sequential bench legs, a rebuilt fleet after drain — must
        # never merge their dispatch counts or overwrite each other's
        # tenant vtime gauges
        self._fleet_eng = next_engine_id()
        self._g_vtime = reg.gauge(
            "ff_fleet_vtime_seconds",
            "Per-tenant virtual device time (used seconds / weight)",
            ("model", "eng"))
        self._c_dispatch = reg.counter(
            "ff_fleet_dispatches_total",
            "Fleet dispatcher packed dispatches across all tenants",
            ("eng",)).labels(eng=self._fleet_eng)
        self._c_shared = reg.counter(
            "ff_fleet_shared_dispatches_total",
            "Extra same-turn dispatches riding a digest-matched "
            "tenant's warm programs (share_identical)",
            ("eng",)).labels(eng=self._fleet_eng)
        self._last_autoscale_t = 0.0  # dispatcher-thread-only
        # per-tenant vtime gauge children, resolved once per tenant —
        # the dispatch loop must not re-run label validation + the
        # family lock per packed dispatch
        self._vtime_children: Dict = {}  # dispatcher-thread-only
        # tenant names whose vtime series the DISPATCHER must reclaim
        # (unload() queues them here: reclaiming from the caller
        # thread raced an in-flight dispatch, whose completion
        # re-created the just-removed series)
        self._vtime_reclaim: List[str] = []  # guarded_by: self._lock

    @staticmethod
    def _swap_hold_n() -> Optional[int]:
        for spec in faults.fleet_faults():
            if spec.kind == "fleet_swap_at_dispatch":
                return int(spec.arg)
        return None

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> "FleetEngine":
        """Build every registry tenant (synchronously — startup is the
        one place a stall is fine), publish them, and start the fleet
        dispatcher."""
        with self._lock:
            if self._stopped:
                raise RuntimeError("fleet was stopped; create a new "
                                   "FleetEngine")
            already = self._thread is not None
        if already:
            return self
        if self.registry is not None:
            for name in self.registry.names():
                if self.registry.spec(name).engine == "draft":
                    # draft entries are built BY the generation tenant
                    # that references them (inside its engine), never
                    # started as standalone tenants
                    continue
                if name not in self._tenants:  # unguarded-ok: pre-thread
                    t = self._build_tenant(self.registry.spec(name))
                    with self._lock:
                        self._tenants[name] = t
        with self._lock:
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._dispatch_loop, name="ff-fleet-dispatch",
                    daemon=True)
                self._thread.start()
        get_logger("serve").event(
            "fleet_start",
            tenants=sorted(self._tenants))  # unguarded-ok: startup log
        return self

    def stop(self) -> None:
        """Serve everything queued to completion, then stop (unbounded
        drain — see :meth:`drain` for the bounded verb)."""
        self.drain(timeout=None)

    def drain(self, timeout: Optional[float] = None) -> Dict:
        """Close every tenant's admission, flush the queues through the
        normal weighted-fair dispatch path, and after ``timeout``
        seconds fail the stragglers with SheddedError.  Returns the
        final per-tenant stats."""
        with self._lock:
            already = self._stopped or self._draining
            self._draining = True
            thread = self._thread
            tenants = list(self._tenants.values())
        if already and thread is None:
            return self.stats()
        for t in tenants:
            t.engine._batcher.close()
        self._wake.set()
        if thread is not None:
            thread.join(timeout)
        with self._lock:
            self._stopped = True
            self._thread = None
            tenants = (list(self._tenants.values())
                       + list(self._retiring))
            self._retiring = []
        shed = 0
        for t in tenants:
            # anything still queued/active past the budget is about to
            # be failed with SheddedError by the engines' own stop():
            # count it so the fleet_drain event reports real losses
            shed += t.engine._batcher.queue_depth
            if t.kind == "generation":
                shed += sum(1 for s in t.engine._slots_state
                            if s is not None)
                t.engine._abort_active()
            t.engine.stop()
        snap = self.stats()
        get_logger("serve").event("fleet_drain", timeout_s=timeout,
                                  shed=shed,
                                  dispatches=self._n_dispatch)
        return snap

    def __enter__(self) -> "FleetEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- tenant construction / publication -----------------------------
    def _build_tenant(self, spec: TenantSpec) -> _Tenant:
        model = build_model(spec, mesh=self.mesh)
        return self._make_tenant(spec, model)

    def _make_tenant(self, spec: TenantSpec, model) -> _Tenant:
        if spec.engine == "generation":
            gkw = dict(spec.generation)
            draft_name = str(gkw.pop("draft", ""))
            if draft_name:
                # the draft tenant compiles + initializes HERE, on the
                # same mesh — its params and draft KV pool live inside
                # this tenant's engine, which is exactly what the gate
                # charged onto this tenant's residency row
                gkw["draft_model"] = build_model(
                    self.registry.spec(draft_name), mesh=self.mesh)
            engine = GenerationEngine(
                model, name=spec.name, clock=self.clock,
                sleep=self._sleep, **gkw)
            engine.begin_external_dispatch()
        else:
            skw = dict(spec.serve)
            engine = ServingEngine(
                model, name=spec.name, clock=self.clock,
                sleep=self._sleep, **skw)
            engine.begin_external_dispatch()
        return _Tenant(spec.name, spec.engine, engine, spec.weight,
                       spec.qps_rows, self.clock())

    def add_engine(self, name: str, engine, weight: float = 1.0,
                   qps_rows: float = 0.0) -> None:
        """Attach an already-constructed engine (must not own a
        dispatcher thread) as a tenant — the programmatic alternative
        to a registry entry.  Published atomically at the next dispatch
        boundary (immediately when the fleet is not running)."""
        kind = ("generation" if isinstance(engine, GenerationEngine)
                else "dense")
        engine.begin_external_dispatch()
        t = _Tenant(name, kind, engine, weight, qps_rows, self.clock())
        self._publish(name, t)

    def load(self, name: str, spec: Optional[TenantSpec] = None,
             wait: bool = True, timeout: Optional[float] = 60.0):
        """Hot load/swap: build ``name`` (from ``spec`` or the
        registry) on a BACKGROUND thread — compile + bucket warmup off
        the serving path — then publish atomically at a dispatch
        boundary.  A swap (existing name) transfers the old engine's
        pending queue to the new one: zero failed in-flight requests.
        Returns the publish event once it landed (``wait=True``) or a
        ``threading.Event`` to wait on."""
        spec = spec or self.registry.spec(name)
        done = threading.Event()
        err: List[BaseException] = []

        def build():
            try:
                t = self._build_tenant(spec)
            except BaseException as e:  # noqa: BLE001 — a failed load
                # must surface as an event + error, never disturb the
                # serving tenants
                err.append(e)
                get_logger("serve").event(
                    "fleet_load_error", model=spec.name,
                    error=f"{type(e).__name__}: {e}"[:300])
                done.set()
                return
            if not self._publish(spec.name, t, on_published=done.set):
                # the fleet stopped while we were building: the
                # tenant was discarded — a wait=True caller must see
                # the failure, not a phantom success
                err.append(RuntimeError(
                    f"fleet stopped before the load of {spec.name!r} "
                    f"could publish"))
                done.set()

        threading.Thread(target=build, name=f"ff-fleet-load-{name}",
                         daemon=True).start()
        if wait:
            if not done.wait(timeout):
                raise TimeoutError(
                    f"fleet load of {name!r} did not publish within "
                    f"{timeout}s")
            if err:
                raise RuntimeError(
                    f"fleet load of {name!r} failed") from err[0]
        return done

    def _publish(self, name: str, tenant: _Tenant,
                 on_published: Optional[Callable] = None) -> bool:
        """Install/queue ``tenant`` under ``name``.  Returns False when
        the fleet already stopped and the tenant was DISCARDED — the
        caller must surface that as a failure, not a landed publish."""
        with self._lock:
            stopped = self._stopped
            running = self._thread is not None and not stopped
            if running:
                self._publishes.append((name, tenant, on_published))
            elif not stopped:
                self._apply_publish(name, tenant)  # guarded by lock
        if stopped:
            # a background load finishing after the fleet shut down:
            # discard loudly instead of installing a tenant nothing
            # will ever dispatch
            tenant.engine.stop()
            get_logger("serve").event("fleet_publish_discarded",
                                      model=name)
            return False
        if running:
            self._wake.set()
        elif on_published is not None:
            on_published()
        return True

    def _apply_publish(self, name, tenant):  # guarded_by: self._lock
        old = self._tenants.get(name)
        # route NEW submissions to the replacement first, then close
        # and drain the outgoing engine's queue into it: a submit
        # racing the swap either lands in the new queue or — in the
        # tiny window where it holds the old engine and hits the
        # closed batcher — fails fast as a typed admission refusal,
        # never as a lost in-flight request
        self._tenants[name] = tenant
        moved: List = []
        retiring = False
        if old is not None:
            # atomic swap: move the already-admitted queue onto the
            # replacement (admitted once = admitted; requeue bypasses
            # admission), carry the fairness clock so a swap is not a
            # priority boost, and retire the old engine with its
            # counters kept for reconciliation
            old.engine._batcher.close()
            moved = old.engine._batcher.fail_pending()
            if moved:
                tenant.engine._batcher.requeue(moved)
            tenant.vtime = old.vtime
            tenant.idle = False
            tenant.carried = dict(old.carried)
            tenant.retired = old.retired + [old.engine.metrics]
            while len(tenant.retired) > _MAX_RETIRED_METRICS:
                # fold the OLDEST retired generation into the static
                # carry and reclaim its registry series — by now its
                # transferred requests have long resolved, so the
                # fold loses nothing while bounding registry growth.
                # The folded counts MOVE into the tenant's eng="carry"
                # series (inc BEFORE removal — a scrape in the window
                # sees a brief double-count, never a backwards counter
                # that Prometheus rate() would read as a reset), so
                # the scraped per-model sums stay monotonic and equal
                # to fleet.stats()'s continuity numbers
                oldest = tenant.retired.pop(0)
                snap = oldest.snapshot()
                for key in _CONTINUITY_KEYS:
                    v = snap.get(key, 0)
                    tenant.carried[key] = (tenant.carried.get(key, 0)
                                           + v)
                    if v:
                        oldest._fams[key].labels(
                            model=oldest.model_tag,
                            eng="carry").inc(v)
                oldest.unregister()
            if old.kind == "generation" and old.engine.has_pending:
                # active decode slots cannot move (their KV state
                # lives in the old engine's cache): keep stepping the
                # old engine until every stream retires — the
                # dispatcher serves retiring tenants alongside live
                # ones, then _finalize_retiring stops them
                retiring = True
                self._retiring.append(old)
            else:
                old.engine.stop()
        get_logger("serve").event(
            "fleet_publish", model=name, swap=old is not None,
            moved_requests=len(moved), retiring_streams=retiring,
            tenants=sorted(self._tenants))

    def unload(self, name: str, timeout: Optional[float] = None) -> Dict:
        """Remove one tenant with ``drain`` semantics: close ITS
        admission, let the fleet dispatcher flush its queue (other
        tenants keep their fair share throughout), then fail
        stragglers after ``timeout`` and detach.  Returns the tenant's
        final stats."""
        with self._lock:
            t = self._tenants.get(name)
        if t is None:
            raise KeyError(f"no resident model {name!r}")
        t.engine._batcher.close()
        self._wake.set()
        deadline = (None if timeout is None
                    else self.clock() + timeout)
        while t.has_pending() or self._in_flight == name:
            if deadline is not None and self.clock() >= deadline:
                break
            self._sleep(0.002)
        with self._lock:
            self._tenants.pop(name, None)
        if t.kind == "generation":
            t.engine._abort_active()
        t.engine.stop()  # fails any stragglers with SheddedError
        snap = self._tenant_stats(t)
        # queue the unloaded tenant's fleet gauge series for the
        # DISPATCHER to reclaim at its next boundary (its own engine
        # series were released by stop()): removing it here raced the
        # tenant's possibly-still-in-flight last dispatch, which would
        # re-create — and permanently resurrect — the stale series
        with self._lock:
            self._vtime_reclaim.append(name)
        if self.autoscaler is not None:
            self.autoscaler.forget(name)
        self._wake.set()
        get_logger("serve").event("fleet_unload", model=name,
                                  pending_failed=int(t.has_pending()))
        return snap

    # ---- producer side -------------------------------------------------
    def _tenant(self, name: str) -> _Tenant:
        with self._lock:
            t = self._tenants.get(name)
        if t is None:
            raise KeyError(f"no resident model {name!r} (have "
                           f"{', '.join(sorted(self.names()))})")
        return t

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def submit(self, name: str, *args, **kw):
        """Route one request to tenant ``name``: dense tenants take the
        per-input row arrays and return a Future; generation tenants
        take a prompt and return a GenerationStream.  Admission
        (bounded queue, deadlines, priorities) is the tenant's own —
        PR 8 semantics unchanged per model."""
        t = self._tenant(name)
        out = t.engine.submit(*args, **kw)
        self._wake.set()
        return out

    def _tenant_stats(self, t: _Tenant) -> Dict:
        snap = t.engine.stats()
        # counter continuity across hot swaps: a tenant's lifetime
        # counters are the sum over every engine generation that
        # served under its name — read LIVE from the retired metrics
        # (see _Tenant.retired) so the reconciliation serve-bench pins
        # holds even for requests that resolved after their swap
        for key, v in t.carried.items():
            if key in snap:
                snap[key] += v
        for m in t.retired:
            old = m.snapshot()
            for key in _CONTINUITY_KEYS:
                if key in snap and key in old:
                    snap[key] += old[key]
        snap.update({"weight": t.weight, "qps_rows_budget": t.qps_rows,
                     "vtime_s": round(t.vtime, 6),
                     "engine_generation": len(t.retired),
                     "resident_bytes": t.resident_bytes()})
        return snap

    def stats(self, name: Optional[str] = None) -> Dict:
        """Per-tenant stats (counters continuous across swaps), or one
        tenant's when ``name`` is given."""
        if name is not None:
            return self._tenant_stats(self._tenant(name))
        with self._lock:
            tenants = dict(self._tenants)
        return {"tenants": {n: self._tenant_stats(t)
                            for n, t in sorted(tenants.items())},
                "dispatches": self._n_dispatch}

    # ---- fleet dispatcher ----------------------------------------------
    def _dispatch_loop(self) -> None:
        while True:
            self._do_publishes()
            self._do_vtime_reclaims()
            self._finalize_retiring()
            self._maybe_autoscale()
            with self._lock:
                draining = self._draining
                tenants = (list(self._tenants.values())
                           + list(self._retiring))
            served = None
            rows0 = 0
            rest: List[_Tenant] = []
            order = self._pick_order(tenants)
            for i, t in enumerate(order):
                rows0 = t.engine.metrics.total_rows
                # a tenant may be backlogged but not DUE (its
                # micro-batcher is inside its coalescing window):
                # dispatch_pending returns None — fall through to the
                # next-lowest virtual time instead of spinning on it
                # (a spin here starved every other tenant for up to
                # max_wait_ms per request, measured as a ~100x skew in
                # the isolation sweep's dispatch counts)
                self._in_flight = t.name
                dt = t.engine.dispatch_pending()
                self._in_flight = None
                if dt is not None:
                    served = t
                    rest = order[i + 1:]
                    break
            if served is None:
                if draining and not any(x.has_pending()
                                        for x in tenants):
                    with self._lock:
                        pending_pub = bool(self._publishes)
                    if not pending_pub:
                        return
                self._wake.wait(self._IDLE_WAIT_S)
                self._wake.clear()
                continue
            t = served
            self._account_dispatch(t, dt, rows0)
            if self.share_identical and rest:
                self._share_turn(t, rest)
            self._maybe_emit_stats()
            if self.pace_s > 0:
                self._sleep(self.pace_s)

    def _account_dispatch(self, t: _Tenant, dt: float,
                          rows0: int) -> None:
        """Charge one completed dispatch to its tenant's fairness
        state + the registry surfaces (dispatcher thread)."""
        self._n_dispatch += 1
        self._c_dispatch.inc()
        with self._lock:
            t.vtime += dt / t.weight
            if t.qps_rows > 0:
                t.allowance -= (t.engine.metrics.total_rows - rows0)
        self._vclock = t.vtime
        # the registry's view of the fairness state fleet_stats
        # reports — same number, two surfaces
        child = self._vtime_children.get(t.name)
        if child is None:
            child = self._g_vtime.labels(model=t.name,
                                         eng=self._fleet_eng)
            self._vtime_children[t.name] = child
        child.set(t.vtime)

    @staticmethod
    def _digest_of(t: _Tenant) -> Optional[str]:
        try:
            return t.engine.model.exec_digest()
        except Exception:  # noqa: BLE001 — an undigestable model just
            # opts out of sharing; it must never poison the dispatcher
            return None

    def _share_turn(self, primary: _Tenant,
                    rest: List[_Tenant]) -> None:
        """Cross-tenant dispatch sharing: serve every OTHER due tenant
        whose model's ``exec_digest()`` matches the primary's in the
        SAME dispatcher turn — identical graphs share compiled
        programs (two checkpoints of one model: same executables,
        different params), so the matched tenants ride the warm
        programs the primary just ran instead of waiting a full SFQ
        rotation.  Each extra dispatch is accounted exactly like a
        primary one (vtime, qps bucket, counters) — sharing a turn is
        a latency optimization, never a fairness subsidy."""
        digest = self._digest_of(primary)
        if digest is None:
            return
        for u in rest:
            if u.kind != primary.kind:
                continue
            if self._digest_of(u) != digest:
                continue
            rows0 = u.engine.metrics.total_rows
            self._in_flight = u.name
            du = u.engine.dispatch_pending()
            self._in_flight = None
            if du is None:
                continue
            self._account_dispatch(u, du, rows0)
            self._c_shared.inc()

    def _maybe_autoscale(self) -> None:
        """Feed the autoscaling policy each tenant's queue depth and
        apply any weight change it returns (dispatcher thread — the
        policy itself is single-threaded by construction)."""
        scaler = self.autoscaler
        if scaler is None:
            return
        now = self.clock()
        with self._lock:
            live = list(self._tenants.values())
        for t in live:
            depth = t.engine._batcher.queue_depth
            new = scaler.observe(t.name, depth, t.weight, now)
            if new is None:
                continue
            with self._lock:
                old, t.weight = t.weight, new
            get_logger("serve").event(
                "fleet_autoscale", model=t.name,
                old_weight=round(old, 4), new_weight=round(new, 4),
                depth=depth)

    def _pick_order(self, tenants: List[_Tenant]) -> List[_Tenant]:
        """Start-time fair queuing: backlogged, within-budget tenants
        in ascending virtual-time order (the dispatcher serves the
        first one with a DUE batch).  A tenant re-entering from idle is
        clamped UP to the global virtual clock (``_vclock``) so idling
        never banks device-time credit — low weight means a smaller
        share while backlogged, never a catch-up monopoly afterwards."""
        now = self.clock()
        ready = []
        for t in tenants:
            t.refill(now)
            if not t.has_pending():
                t.idle = True
                continue
            if t.idle:
                t.vtime = max(t.vtime, self._vclock)
                t.idle = False
            if t.within_budget():
                ready.append(t)
        ready.sort(key=lambda t: (t.vtime, t.name))
        return ready

    def _do_vtime_reclaims(self) -> None:
        """Drop unloaded tenants' vtime gauge series (dispatcher
        thread — after this point no dispatch can re-create them: the
        tenant left ``_tenants`` before its name was queued here)."""
        with self._lock:
            if not self._vtime_reclaim:
                return
            names, self._vtime_reclaim = self._vtime_reclaim, []
        for name in names:
            self._vtime_children.pop(name, None)
            self._g_vtime.remove(model=name, eng=self._fleet_eng)

    def _finalize_retiring(self) -> None:
        """Stop swapped-out generation engines whose last active
        stream has retired (dispatcher thread)."""
        with self._lock:
            done = [t for t in self._retiring if not t.has_pending()]
            if not done:
                return
            self._retiring = [t for t in self._retiring
                              if t.has_pending()]
        for t in done:
            t.engine.stop()
            get_logger("serve").event("fleet_retired", model=t.name)

    def _do_publishes(self) -> None:
        """Apply queued atomic publishes at the dispatch boundary.
        Under ``fleet_swap_at_dispatch:N`` they are HELD until fleet
        dispatch index N (deterministic swap timing for tests)."""
        with self._lock:
            if not self._publishes:
                return
            if (self._swap_hold is not None
                    and self._n_dispatch < self._swap_hold
                    and not self._draining):
                # held for the fault's pinned dispatch index — but a
                # drain overrides the hold, or shutdown would wait on
                # a dispatch that will never happen
                return
            pubs, self._publishes = self._publishes, []
            for name, tenant, cb in pubs:
                self._apply_publish(name, tenant)
        for _, _, cb in pubs:
            if cb is not None:
                cb()

    def _maybe_emit_stats(self) -> None:
        now = self.clock()
        if self.stats_every_s <= 0:
            return
        if now - self._last_stats_t < self.stats_every_s:
            return
        self._last_stats_t = now
        with self._lock:
            shares = {t.name: round(t.vtime, 4)
                      for t in self._tenants.values()}
        get_logger("serve").event(
            "fleet_stats", dispatches=self._n_dispatch, vtime=shares)


__all__ = ["FleetEngine"]
