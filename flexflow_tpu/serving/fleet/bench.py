"""``serve-bench --fleet`` — multi-tenant isolation & hot-swap
benchmark (docs/serving.md "Model fleets").

Three questions, three legs, one JSON artifact
(``artifacts/fleet_bench_r*.json``):

1. **capacity** — each tenant's solo max-rate throughput on this mesh
   (its fair-share denominator);
2. **isolation** — tenant A is offered 2x ITS capacity (bounded queue,
   ``shed_oldest`` + deadlines — PR 8's overload regime, per tenant)
   while tenant B runs at a moderate rate; the acceptance criterion is
   that B's goodput (completions within the SLO) stays >= 90% of its
   SOLO goodput at the same offered rate — overload on A must burn A's
   queue and A's fair share, never B's;
3. **hot swap** — while A serves paced load, a new checkpoint for A is
   built on the background thread and atomically published at a
   dispatch boundary; the criterion is ZERO failed in-flight requests
   and exact counter reconciliation across the swap
   (``submitted == completed + rejected + shed + expired + errors``,
   counters continuous over the engine generations).

Run: ``python -m flexflow_tpu.cli serve-bench --fleet [--requests N]
[--cell-seconds S] [--out f.json]``.  Fully measurable on CPU — the
fairness being exercised is dispatcher policy, not silicon.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

NFEAT = 16
NCLS = 10


def _dense_builder(hidden: int, seed: int):
    def build(cfg):
        import flexflow_tpu as ff
        from flexflow_tpu.parallel.mesh import MachineMesh
        cfg.seed = seed
        m = ff.FFModel(cfg, mesh=MachineMesh({"n": 1}))
        x = m.create_tensor((cfg.batch_size, NFEAT), name="x")
        t = m.dense(x, hidden, activation="relu")
        t = m.dense(t, NCLS)
        return m
    return build


def _registry(max_batch: int, hidden_a: int, hidden_b: int,
              queue_rows: int, seed: int, bounded: bool = True):
    """Two dense tenants; A's queue is bounded (shed_oldest) unless
    ``bounded=False`` — the capacity legs submit back-to-back, which a
    bounded queue would shed instead of measuring."""
    from .registry import ModelRegistry
    reg = ModelRegistry()
    a_serve = {"max_wait_ms": 1.0, "stats_every": 0}
    if bounded:
        a_serve.update({"max_queue_rows": queue_rows,
                        "admission": "shed_oldest"})
    reg.register(
        "a", _dense_builder(hidden_a, seed), batch_size=max_batch,
        weight=1.0, serve=a_serve)
    reg.register(
        "b", _dense_builder(hidden_b, seed + 1), batch_size=max_batch,
        weight=1.0,
        serve={"max_wait_ms": 1.0, "stats_every": 0})
    return reg


def _requests(n: int, rows_lo: int, rows_hi: int, seed: int
              ) -> List[np.ndarray]:
    rng = np.random.default_rng(seed)
    sizes = rng.integers(rows_lo, rows_hi + 1, n)
    return [rng.standard_normal((int(s), NFEAT)).astype(np.float32)
            for s in sizes]


def _arrivals(n: int, rate: float, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed + 1)
    return np.cumsum(rng.exponential(1.0 / max(rate, 1e-9), n))


def _measure_capacity(fleet, name: str, pool) -> float:
    """Requests/s with every request submitted back-to-back — the
    tenant's solo ceiling under the fleet dispatcher.  One warm lap
    (compile caches, branch predictors) then best-of-2 measured legs —
    host hiccups only ever DEFLATE a wall-clock sample (bench.py's
    min-of-legs philosophy), and the isolation leg's offered rates are
    derived from these numbers, so a noisy ceiling would distort the
    whole sweep."""
    def lap():
        t0 = time.perf_counter()
        futs = [fleet.submit(name, r) for r in pool]
        for f in futs:
            f.result(timeout=120)
        return len(pool) / (time.perf_counter() - t0)

    lap()  # warm
    return max(lap(), lap())


class _Pacer(threading.Thread):
    """Open-loop Poisson replay of one tenant's trace: submits at the
    scheduled arrival times, records per-request completion/latency via
    done-callbacks, counts admission refusals."""

    def __init__(self, fleet, name: str, reqs, rate: float,
                 deadline_ms: Optional[float]):
        super().__init__(name=f"pacer-{name}", daemon=True)
        self.fleet, self.tenant = fleet, name
        self.reqs, self.rate = reqs, rate
        self.deadline_ms = deadline_ms
        self.entries: List[Dict] = []
        self.rejected = 0
        self.submitted = 0

    def run(self):
        from ..errors import OverloadError
        arrivals = _arrivals(len(self.reqs), self.rate,
                             hash(self.tenant) % 1000)
        t0 = time.perf_counter()
        for r, at in zip(self.reqs, arrivals):
            lag = t0 + at - time.perf_counter()
            # always yield: an overload pacer that never sleeps would
            # spin the GIL and starve the DISPATCHER — measuring the
            # bench harness's convoy effect, not the fleet's isolation
            # (a real overload arrives over the network, not from a
            # tight same-process loop)
            time.sleep(max(lag, 0.0))
            ts = time.perf_counter()
            self.submitted += 1
            try:
                fut = self.fleet.submit(self.tenant, r,
                                        deadline_ms=self.deadline_ms)
            except OverloadError:
                self.rejected += 1
                continue
            entry = {"rows": int(r.shape[0]), "t": ts, "t_done": None,
                     "ok": False}

            def cb(f, e=entry):
                e["t_done"] = time.perf_counter()
                e["ok"] = f.exception() is None and not f.cancelled()

            fut.add_done_callback(cb)
            self.entries.append(entry)

    def result_row(self, slo_ms: float) -> Dict:
        done = [e for e in self.entries
                if e["ok"] and e["t_done"] is not None]
        lats = [(e["t_done"] - e["t"]) * 1e3 for e in done]
        good = [e for e, l in zip(done, lats) if l <= slo_ms]
        # goodput normalizes by at least the INTENDED trace duration:
        # a pacer that briefly fell behind schedule would otherwise
        # compress its span and report goodput above the offered rate
        span = max(1e-6, len(self.reqs) / max(self.rate, 1e-9),
                   (max((e["t_done"] for e in done), default=0)
                    - min((e["t"] for e in self.entries), default=0)))
        from ...profiling import quantiles
        q = quantiles(lats)

        def ms(v):
            return None if v != v else round(v, 3)

        return {
            "offered_rps": round(self.rate, 2),
            "offered_requests": self.submitted,
            "completed": len(done),
            "good_requests": len(good),
            "good_rows": int(sum(e["rows"] for e in good)),
            "goodput_rps": round(len(good) / span, 2),
            "rejected_at_submit": self.rejected,
            "p50_ms": ms(q[0.5]), "p95_ms": ms(q[0.95]),
            "p99_ms": ms(q[0.99]),
        }


def _reconciled(stats: Dict, submitted: int) -> bool:
    """Every submitted request accounted for exactly once — across hot
    swaps the fleet's merged counters must keep this identity."""
    return (stats["requests"] + stats["rejected"] + stats["shed"]
            + stats["expired"] + stats["errors"]) == submitted


def run_fleet_bench(requests: int = 384, rows_lo: int = 1,
                    rows_hi: int = 8, max_batch: int = 32,
                    hidden_a: int = 256, hidden_b: int = 256,
                    queue_rows: int = 0, cell_seconds: float = 2.0,
                    slo_ms: float = 0.0, b_frac: float = 0.15,
                    seed: int = 0) -> Dict:
    """The full three-leg benchmark; returns the JSON payload."""
    import jax

    from ...search.calibration import device_kind as _device_kind
    from .engine import FleetEngine

    queue_rows = queue_rows or 4 * max_batch
    pool = _requests(requests, rows_lo, rows_hi, seed)

    # ---- leg 0: per-tenant solo capacity --------------------------------
    caps: Dict[str, float] = {}
    for name in ("a", "b"):
        reg1 = _registry(max_batch, hidden_a, hidden_b, queue_rows,
                         seed, bounded=False)
        with FleetEngine(_one_of(reg1, name)) as fleet:
            caps[name] = _measure_capacity(fleet, name, pool)
    if slo_ms <= 0:
        # generous at the offered rates below, hopeless for an
        # unbounded backlog — same auto-SLO philosophy as --overload
        slo_ms = max(50.0, 4e3 / max(caps["b"], 1.0) * 8)
    rate_b = max(1.0, caps["b"] * b_frac)
    rate_a_over = max(1.0, caps["a"] * 2.0)

    def n_for(rate):
        return max(16, min(4096, int(rate * cell_seconds)))

    def reqs_for(rate):
        n = n_for(rate)
        return [pool[i % len(pool)] for i in range(n)]

    # ---- leg 1: B solo at its moderate rate -----------------------------
    reg_solo = _registry(max_batch, hidden_a, hidden_b, queue_rows, seed)
    with FleetEngine(_one_of(reg_solo, "b")) as fleet:
        pb = _Pacer(fleet, "b", reqs_for(rate_b), rate_b, None)
        pb.start()
        pb.join()
        fleet.drain(timeout=max(1.0, 4 * slo_ms / 1e3))
        solo_b = pb.result_row(slo_ms)
        solo_stats = fleet.stats("b")
    solo_b["reconciled"] = _reconciled(solo_stats, pb.submitted)

    # ---- leg 2: isolation — A at 2x its capacity, B unchanged -----------
    reg2 = _registry(max_batch, hidden_a, hidden_b, queue_rows, seed)
    with FleetEngine(reg2) as fleet:
        pa = _Pacer(fleet, "a", reqs_for(rate_a_over), rate_a_over,
                    deadline_ms=slo_ms)
        pb = _Pacer(fleet, "b", reqs_for(rate_b), rate_b, None)
        pa.start(); pb.start()
        pa.join(); pb.join()
        fleet.drain(timeout=max(1.0, 4 * slo_ms / 1e3))
        contended_a = pa.result_row(slo_ms)
        contended_b = pb.result_row(slo_ms)
        stats_a = fleet.stats("a")
        stats_b = fleet.stats("b")
    contended_a["reconciled"] = _reconciled(stats_a, pa.submitted)
    contended_b["reconciled"] = _reconciled(stats_b, pb.submitted)
    contended_a["peak_queue_rows"] = stats_a["peak_queue_rows"]
    contended_a["shed"] = stats_a["shed"]
    contended_a["expired"] = stats_a["expired"]

    # ---- leg 3: hot checkpoint swap under load --------------------------
    # UNBOUNDED admission here: the question is whether the SWAP fails
    # anything, so load management (shed_oldest under the compile's CPU
    # contention) must not be able to fail requests for its own reasons
    reg3 = _registry(max_batch, hidden_a, hidden_b, queue_rows, seed,
                     bounded=False)
    swap_row: Dict = {}
    with FleetEngine(reg3) as fleet:
        rate_a = max(1.0, caps["a"] * 0.5)
        pa = _Pacer(fleet, "a", reqs_for(rate_a), rate_a, None)
        pa.start()
        time.sleep(cell_seconds * 0.25)
        # "new checkpoint": same graph, fresh init (a different seed) —
        # built on the background thread, published at a dispatch
        # boundary, pending queue transferred
        reg3.register(
            "a", _dense_builder(hidden_a, seed + 99),
            batch_size=max_batch,
            serve={"max_wait_ms": 1.0, "stats_every": 0})
        t_swap0 = time.perf_counter()
        fleet.load("a", wait=True)
        swap_s = time.perf_counter() - t_swap0
        pa.join()
        fleet.drain(timeout=max(2.0, 8 * slo_ms / 1e3))
        stats = fleet.stats("a")
    failed = sum(1 for e in pa.entries
                 if e["t_done"] is not None and not e["ok"])
    swap_row = {
        "offered_rps": round(rate_a, 2),
        "swap_publish_s": round(swap_s, 4),
        "engine_generations": stats["engine_generation"] + 1,
        "in_flight_failed": failed,
        "completed": sum(1 for e in pa.entries if e["ok"]),
        "rejected_at_submit": pa.rejected,
        "reconciled": _reconciled(stats, pa.submitted),
        "counters": {k: stats[k] for k in
                     ("requests", "rejected", "shed", "expired",
                      "errors")},
    }

    ratio = (contended_b["goodput_rps"]
             / max(1e-6, solo_b["goodput_rps"]))
    return {
        "bench": "fleet-bench",
        "backend": jax.default_backend(),
        "device_kind": _device_kind(),
        "config": {
            "requests_pool": requests, "rows": f"{rows_lo}-{rows_hi}",
            "max_batch": max_batch, "hidden_a": hidden_a,
            "hidden_b": hidden_b, "queue_rows": queue_rows,
            "cell_seconds": cell_seconds, "slo_ms": round(slo_ms, 3),
            "b_frac": b_frac, "seed": seed,
        },
        "capacity_rps": {k: round(v, 2) for k, v in caps.items()},
        "solo_b": solo_b,
        "contended_a_2x": contended_a,
        "contended_b": contended_b,
        "swap": swap_row,
        "summary": {
            "b_goodput_solo_rps": solo_b["goodput_rps"],
            "b_goodput_contended_rps": contended_b["goodput_rps"],
            "b_goodput_ratio": round(ratio, 4),
            "isolation_holds": ratio >= 0.9,
            "a_queue_bounded": contended_a["peak_queue_rows"]
            <= queue_rows,
            "swap_zero_failed": swap_row["in_flight_failed"] == 0,
            "swap_reconciled": swap_row["reconciled"],
        },
    }


def _one_of(reg, name):
    """A registry view containing only ``name`` (solo legs)."""
    from .registry import ModelRegistry
    out = ModelRegistry()
    out.hbm_gb = reg.hbm_gb
    out._specs[name] = reg.spec(name)
    return out


def validate_fleet_bench_json(obj) -> List[str]:
    """Schema problems of a fleet-bench artifact (repo static gate —
    scripts/check_fleet_artifacts.py).  Returns problem strings."""
    probs: List[str] = []
    if not isinstance(obj, dict):
        return ["artifact must be a JSON object"]
    if obj.get("bench") != "fleet-bench":
        probs.append(f"bench: want 'fleet-bench', got {obj.get('bench')!r}")
    for key in ("config", "capacity_rps", "solo_b", "contended_a_2x",
                "contended_b", "swap", "summary"):
        if not isinstance(obj.get(key), dict):
            probs.append(f"{key}: want an object")
    summary = obj.get("summary") or {}
    for key in ("b_goodput_ratio", "b_goodput_solo_rps",
                "b_goodput_contended_rps"):
        if not isinstance(summary.get(key), (int, float)):
            probs.append(f"summary.{key}: want a number")
    for key in ("isolation_holds", "swap_zero_failed",
                "swap_reconciled"):
        if not isinstance(summary.get(key), bool):
            probs.append(f"summary.{key}: want a bool")
    swap = obj.get("swap") or {}
    if not isinstance(swap.get("in_flight_failed"), int):
        probs.append("swap.in_flight_failed: want an int")
    return probs


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="flexflow-tpu serve-bench --fleet",
        description="multi-tenant isolation + hot-swap benchmark "
                    "(docs/serving.md 'Model fleets')")
    ap.add_argument("--requests", type=int, default=384)
    ap.add_argument("--rows", default="1-8")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--hidden-a", type=int, default=256)
    ap.add_argument("--hidden-b", type=int, default=256)
    ap.add_argument("--queue-rows", type=int, default=0,
                    help="tenant A's bounded queue (0 = 4x max-batch)")
    ap.add_argument("--cell-seconds", type=float, default=2.0)
    ap.add_argument("--slo-ms", type=float, default=0.0)
    ap.add_argument("--b-frac", type=float, default=0.15,
                    help="tenant B's offered rate as a fraction of its "
                         "solo (backlogged) capacity — keep it under "
                         "B's FAIR-SHARE paced capacity: the isolation "
                         "question is whether A's overload drags B, "
                         "not whether B can exceed its own share")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    try:
        lo, hi = (int(v) for v in args.rows.split("-"))
    except ValueError:
        ap.error(f"--rows wants LO-HI, got {args.rows!r}")
    from ...fflogger import silenced
    with silenced("ff", "serve"):
        payload = run_fleet_bench(
            requests=args.requests, rows_lo=lo, rows_hi=hi,
            max_batch=args.max_batch, hidden_a=args.hidden_a,
            hidden_b=args.hidden_b, queue_rows=args.queue_rows,
            cell_seconds=args.cell_seconds, slo_ms=args.slo_ms,
            b_frac=args.b_frac, seed=args.seed)
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
