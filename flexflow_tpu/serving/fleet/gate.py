"""Static co-residency gate — do N models FIT on one mesh?
(docs/serving.md "Model fleets"; ``flexflow-tpu lint --fleet`` /
``explain --fleet``.)

Entirely device-free: per tenant it builds the registry's UNCOMPILED
graph, resolves its strategy, and computes

* ``ff108_bytes`` — the per-device peak through the SAME accounting the
  single-model FF108 gate and the search's legality check use
  (``Simulator.peak_memory_bytes`` x the compiler-temp factor, with
  ``opt_slot_bytes=0``: a serving tenant holds no optimizer state),
  plus the KV cache for generation tenants;
* ``resident_bytes`` — the always-resident part alone: per-device
  parameter bytes placed by THE tracer's own ``param_spec`` (over the
  device-free AbstractMesh — the PR 9 shared-placement guarantee) plus
  ``analysis.kv_memory.kv_cache_bytes``.  This number is pinned
  byte-for-byte against the engine's real allocations
  (``FleetEngine.stats()[..]["resident_bytes"]``,
  tests/test_fleet.py) — the gate and the runtime cannot disagree.

The fleet verdict sums ``ff108_bytes`` across tenants: over the HBM
budget → **FF130** (ERROR — lint exits 1); each tenant contributes an
**FF131** INFO breakdown row either way.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ...analysis.diagnostics import DiagnosticReport, make
from ...analysis.kv_memory import (DEFAULT_PAGE_SIZE, default_serve_seq,
                                   dtype_bytes, kv_cache_bytes,
                                   kv_page_plan)
from ...analysis.strategy_passes import infer_mesh_shape
from ...parallel.mesh import AbstractMesh
from .registry import ModelRegistry, TenantSpec

# parameters are held in the f32 master dtype (FFConfig.param_dtype)
PARAM_BYTES = 4


def _subaxis_sizes(mesh: AbstractMesh) -> Dict[str, int]:
    """size of every axis name a PartitionSpec entry can mention:
    canonical axes ("n") and their prime sub-axes ("n0", "n1", ...)."""
    out: Dict[str, int] = {}
    for a, size in mesh.sizes.items():
        out[a] = size
        for nm, f in zip(mesh.subaxes(a), mesh._subfactors[a]):
            out[nm] = f
    return out


def static_params_bytes(layers, strategies, mesh: AbstractMesh) -> float:
    """Per-device parameter bytes under the strategy — placed by the
    SAME ``param_spec`` the tracer uses (on the AbstractMesh), so the
    static number equals what ``init_layers`` actually allocates per
    device."""
    from ...parallel.sharding import param_spec
    sizes = _subaxis_sizes(mesh)
    total = 0.0
    for op in layers:
        pc = (strategies or {}).get(op.name)
        for w in op.weights:
            spec = param_spec(w, pc, mesh, on_fallback=lambda *a: None)
            parts = 1
            for entry in spec:
                if entry is None:
                    continue
                names = ((entry,) if isinstance(entry, str)
                         else tuple(entry))
                for nm in names:
                    parts *= sizes.get(nm, 1)
            vol = 1
            for s in w.shape:
                vol *= int(s)
            total += vol * PARAM_BYTES / parts
    return total


def model_residency(spec: TenantSpec, layers, input_tensors, strategies,
                    mesh_shape: Optional[Dict[str, int]] = None,
                    device_spec=None,
                    xla_temp_factor: Optional[float] = None,
                    compute_dtype: str = "float32",
                    model_config=None, draft=None) -> Dict:
    """One tenant's per-device memory prediction (see module
    docstring).  ``mesh_shape`` defaults to the strategy-inferred mesh
    (exactly like ``lint``).  ``model_config`` (the built tenant's
    FFConfig) supplies the SAME fallbacks the GenerationEngine resolves
    — page geometry (``serve_kv_page``/``serve_kv_pages``) and the
    compute dtype — so a knob set in the builder's config rather than
    the fleet spec still reaches the gate's accounting.

    ``draft`` — ``(draft_name, draft_layers, draft_strategies)`` when
    the tenant's generation section references a speculative-decoding
    draft entry: the draft's params PLUS its own KV page pool (SAME
    slots/seq/page geometry/dtype as the target — the engine mirrors
    positions 1:1) are charged onto this tenant's residency, because
    that is exactly what its GenerationEngine allocates."""
    from ...search.cost_model import XLA_TEMP_FACTOR, spec_for_device
    from ...search.simulator import Simulator

    device_spec = device_spec or spec_for_device()
    factor = (float(xla_temp_factor) if xla_temp_factor
              else XLA_TEMP_FACTOR)
    if mesh_shape is None:
        if strategies:
            mesh_shape, _ = infer_mesh_shape(strategies, layers, 10 ** 9)
        else:
            # no strategy: the tenant serves replicated — every device
            # holds the full model, so the per-device view is {n: 1}
            mesh_shape = {"n": 1}
    mesh = AbstractMesh(mesh_shape)
    kv = 0.0
    slots = seq = 0
    kv_pages = kv_page = 0
    plan = None
    if model_config is not None:
        compute_dtype = getattr(model_config, "compute_dtype",
                                compute_dtype)
    if spec.engine == "generation":
        slots = int(spec.generation.get("slots", 8))
        seq = (int(spec.generation.get("max_seq", 0))
               or default_serve_seq(input_tensors) or 0)
        # the tenant's paged-KV geometry: the SAME resolution chain
        # the GenerationEngine runs — spec key, else the builder's
        # FFConfig, else the kv_memory defaults — so gate and runtime
        # integrate one pool no matter where the knob was set
        kv_page = (int(spec.generation.get("page_size", 0))
                   or int(getattr(model_config, "serve_kv_page", 0)))
        kv_pages = (int(spec.generation.get("num_pages", 0))
                    or int(getattr(model_config, "serve_kv_pages", 0)))
        if slots > 0 and seq > 0:
            plan = kv_page_plan(layers, mesh_shape, slots, seq,
                                kv_dtype_bytes=dtype_bytes(compute_dtype),
                                page_size=kv_page or DEFAULT_PAGE_SIZE,
                                num_pages=kv_pages)
            kv = plan["total_bytes"]
    sim = Simulator(spec=device_spec,
                    num_devices=max(1, mesh.mesh_product),
                    use_native=False, opt_slot_bytes=0)
    peak = sim.peak_memory_bytes(layers, strategies or {}, mesh_shape,
                                 assume_remat=False) * factor
    params = static_params_bytes(layers, strategies, mesh)
    quant_delta = 0.0
    if getattr(spec, "quantize", "") == "int8":
        # int8 weight-quantized tenant (ISSUE 14): the eligible f32
        # kernel shards are replaced by int8 shards + replicated
        # per-channel scales — the SAME eligibility predicate and
        # placement rules quantize_params applies at engine warmup,
        # so resident_bytes stays pinned byte-for-byte against the
        # engine's real allocation
        from ..quantize import quantized_params_bytes_delta
        quant_delta = quantized_params_bytes_delta(layers, strategies,
                                                   mesh)
        params += quant_delta
    draft_name = ""
    draft_bytes = 0.0
    if draft is not None:
        draft_name, draft_layers, draft_strategies = draft
        draft_bytes = static_params_bytes(draft_layers,
                                          draft_strategies, mesh)
        if slots > 0 and seq > 0:
            draft_bytes += kv_cache_bytes(
                draft_layers, mesh_shape, slots, seq,
                kv_dtype_bytes=dtype_bytes(compute_dtype),
                page_size=kv_page or DEFAULT_PAGE_SIZE,
                num_pages=kv_pages)
    role = getattr(spec, "role", "mixed")
    staging = 0.0
    if plan is not None and role == "prefill":
        # disaggregated prefill engines (ISSUE 19): at migration one
        # stream's covering page chain is materialized as a contiguous
        # staging copy (export_pages' gather feeding the device_get).
        # Transient, but the FF132 topology contract charges one
        # chain's worth as prefill headroom so the gate and the router
        # cannot diverge on whether a migrating fleet fits.
        staging = plan["pages_per_slot"] * plan["page_bytes"]
    return {
        "name": spec.name,
        "engine": spec.engine,
        "role": role,
        "mesh": {a: s for a, s in mesh_shape.items() if s > 1} or {"n": 1},
        "params_bytes": params,
        "quantize": getattr(spec, "quantize", ""),
        "quantize_bytes_delta": quant_delta,
        "kv_bytes": kv,
        "kv_slots": slots,
        "kv_seq": seq,
        # resolved page geometry (0 = not a sized generation tenant):
        # the FF132 disagg checks compare these across roles
        "kv_page_size": plan["page_size"] if plan else 0,
        "kv_num_pages": plan["num_pages"] if plan else 0,
        "kv_pages_per_slot": plan["pages_per_slot"] if plan else 0,
        "staging_bytes": staging,
        "draft": draft_name,
        "draft_bytes": draft_bytes,
        # the byte-for-byte pin vs the engine's real allocation (the
        # staging copy is a migration-time transient, NOT part of the
        # always-resident pin)
        "resident_bytes": params + kv + draft_bytes,
        # the gate quantity: FF108 accounting + the unscaled KV scalar
        # (a preallocated buffer has no XLA temps — same rule as the
        # single-model lint --serve-slots path).  The quantization
        # delta rides UNSCALED too, like the KV cache: an int8 buffer
        # swap has no XLA-temp component.  The draft's params + pool
        # are preallocated residency of the SAME kind.  Prefill-role
        # tenants additionally carry the migration staging chain.
        "ff108_bytes": peak + kv + quant_delta + draft_bytes + staging,
    }


def resolve_budget(hbm_gb: float, device_spec=None) -> float:
    """The per-device HBM budget in bytes: an explicit ``hbm_gb``
    override, else the device spec's capacity — the ONE resolution
    rule shared by the FF130 gate and ``explain --fleet``'s verdict
    (they must never disagree on the same registry)."""
    from ...search.cost_model import spec_for_device
    device_spec = device_spec or spec_for_device()
    return hbm_gb * 1e9 if hbm_gb > 0 else device_spec.hbm_capacity


def fleet_gate_report(registry: ModelRegistry,
                      hbm_gb: float = 0.0,
                      device_spec=None,
                      xla_temp_factor: Optional[float] = None
                      ) -> Tuple[DiagnosticReport, List[Dict]]:
    """The co-residency verdict for a whole registry: per-tenant
    residency rows (FF131 INFO) and the summed-vs-HBM gate (FF130
    ERROR when the fleet does not fit).  ``hbm_gb`` overrides the
    device spec's HBM capacity (the registry file's ``hbm_gb`` is the
    caller's usual source)."""
    from ...search.cost_model import spec_for_device

    device_spec = device_spec or spec_for_device()
    hbm = resolve_budget(hbm_gb, device_spec)
    report = DiagnosticReport()
    rows: List[Dict] = []
    total = 0.0
    for name in registry.names():
        spec = registry.spec(name)
        if spec.engine == "draft":
            # draft entries are charged onto the tenant that references
            # them (exactly where their params + pool live at runtime),
            # never as standalone rows — a double count would fail
            # fleets that actually fit
            continue
        model, strategies = registry.graph(name)
        draft = None
        dname = str(spec.generation.get("draft", ""))
        if dname:
            dmodel, dstrat = registry.graph(dname)
            draft = (dname, dmodel.layers, dstrat)
        row = model_residency(spec, model.layers, model.input_tensors,
                              strategies, device_spec=device_spec,
                              xla_temp_factor=xla_temp_factor,
                              model_config=model.config, draft=draft)
        rows.append(row)
        total += row["ff108_bytes"]
        kv_note = (f" + {row['kv_bytes'] / 1e9:.2f} GB KV "
                   f"({row['kv_slots']} slots x {row['kv_seq']})"
                   if row["kv_bytes"] else "")
        draft_note = (f" + {row['draft_bytes'] / 1e9:.2f} GB draft "
                      f"({row['draft']})" if row["draft_bytes"] else "")
        report.add(make(
            "FF131", name,
            f"[{row['engine']}] mesh {row['mesh']}: "
            f"{row['ff108_bytes'] / 1e9:.2f} GB peak "
            f"({row['params_bytes'] / 1e9:.2f} GB params{kv_note}"
            f"{draft_note})"))
    # ---- FF132: disaggregated-topology checks (ISSUE 19) ------------
    # A role-tagged fleet is a migration contract: the router ships KV
    # page chains from prefill-role tenants into decode-role pools, so
    # the gate must refuse topologies the migration protocol cannot
    # serve — BEFORE the first stream fails at import time.
    gen_rows = [r for r in rows if r["engine"] == "generation"]
    prefill_rows = [r for r in gen_rows if r["role"] == "prefill"]
    decode_rows = [r for r in gen_rows if r["role"] == "decode"]
    if prefill_rows and not any(r["role"] in ("decode", "mixed")
                                for r in gen_rows):
        report.add(make(
            "FF132", "",
            f"prefill-role tenant(s) "
            f"{[r['name'] for r in prefill_rows]} have no decode/mixed "
            f"migration target in this fleet",
            hint="tag a generation tenant role='decode' (or 'mixed') "
                 "or drop the prefill tag — a prefill engine with "
                 "nowhere to migrate decodes co-located forever"))
    for r in decode_rows:
        need = r["kv_slots"] * r["kv_pages_per_slot"]
        if need and r["kv_num_pages"] < need:
            report.add(make(
                "FF132", r["name"],
                f"decode pool has {r['kv_num_pages']} pages but "
                f"adopting {r['kv_slots']} migrated full-length "
                f"streams needs {need} "
                f"({r['kv_pages_per_slot']} pages x {r['kv_slots']} "
                f"slots)",
                hint="migrated chains arrive at full prompt length "
                     "with no shared-prefix guarantee — size "
                     "num_pages to slots x ceil(max_seq/page_size) "
                     "or lower slots"))
    role_sizes = {r["kv_page_size"] for r in gen_rows
                  if r["role"] != "mixed" and r["kv_page_size"]}
    if len(role_sizes) > 1:
        report.add(make(
            "FF132", "",
            f"prefill/decode tenants disagree on page_size "
            f"{sorted(role_sizes)} — import_pages requires identical "
            f"page geometry on both ends",
            hint="set one generation.page_size across every "
                 "role-tagged tenant"))
    if total > hbm:
        worst = max(rows, key=lambda r: r["ff108_bytes"])
        report.add(make(
            "FF130", "",
            f"fleet of {len(rows)} model(s) needs "
            f"{total / 1e9:.2f} GB per device, budget is "
            f"{hbm / 1e9:.2f} GB; largest tenant: {worst['name']} "
            f"({worst['ff108_bytes'] / 1e9:.2f} GB)",
            hint="unload a tenant, shard the largest one wider, or "
                 "serve on more HBM — the same fleet minus one model "
                 "may already pass"))
    return report, rows


__all__ = ["fleet_gate_report", "model_residency", "resolve_budget",
           "static_params_bytes"]
