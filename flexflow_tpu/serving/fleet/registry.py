"""Model fleet registry — name → (builder, checkpoint, strategy, engine
kind, fairness/admission knobs) for multi-tenant serving
(docs/serving.md "Model fleets").

A fleet is declared either programmatically (``ModelRegistry().
register(...)``) or as a JSON file (``ModelRegistry.from_file``)::

    {
      "fleet": [
        {"name": "ranker", "model": "transformer", "engine": "dense",
         "strategy": "artifacts/searched_transformer_b32_8dev.pb",
         "checkpoint": "ckpts/ranker.npz",
         "weight": 2.0, "qps_rows": 0, "batch_size": 32,
         "serve": {"max_queue_rows": 128, "admission": "shed_oldest"}},
        {"name": "chat", "model": "transformer_lm",
         "engine": "generation",
         "generation": {"slots": 8, "max_seq": 64, "eos_id": 0}}
      ],
      "hbm_gb": 16.0
    }

``model`` names a builtin graph builder (the same registry ``flexflow-
tpu lint --model`` uses, plus the LM builders for generation tenants);
programmatic registration accepts any ``builder(cfg) -> FFModel``.
The registry is deliberately split from the engine: ``graph()`` builds
the UNCOMPILED graph device-free (the static co-residency gate lints a
64-chip fleet from a laptop — fleet/gate.py), while ``build()``
compiles + initializes + restores the checkpoint for actual serving
(fleet/engine.py).

``validate_fleet_json`` is the ONE schema check, shared by
``ModelRegistry.from_json``, ``flexflow-tpu lint --fleet`` and the repo
static gate (scripts/check_fleet_artifacts.py) so a committed fleet
file can never rot silently.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional

# roles a host/tenant may take in a disaggregated cluster (ISSUE 19,
# docs/serving.md "Disaggregated prefill/decode"): "prefill" engines
# serve prefill chunks then migrate the KV page chain out, "decode"
# engines adopt migrated streams and dispatch nothing but decode
# steps, "mixed" (the default) does both co-located
TENANT_ROLES = ("prefill", "decode", "mixed")

# "draft" tenants are graphs co-hosted ONLY as a generation tenant's
# speculative-decoding draft (referenced via generation.draft): the
# fleet builds their params but never starts an engine for them — the
# referencing tenant's GenerationEngine drives the draft directly
ENGINE_KINDS = ("dense", "generation", "draft")

# knobs a fleet entry may override per engine kind; validated here so a
# typo'd knob fails at load, not as an ignored key
_SERVE_KEYS = frozenset((
    "max_batch", "max_wait_ms", "buckets", "max_queue_rows", "admission",
    "starvation_ms", "stats_every"))
_GEN_KEYS = frozenset((
    "slots", "max_seq", "max_new_tokens", "eos_id", "max_queue_requests",
    "admission", "starvation_ms", "stats_every",
    # paged KV knobs (ISSUE 15): the co-residency gate reads the SAME
    # keys (serving/fleet/gate.py), so a tenant's page geometry and its
    # FF130 accounting cannot diverge
    "page_size", "num_pages", "prefill_chunk", "prefix_cache",
    # speculative decoding (ISSUE 16): "draft" names a co-registered
    # engine="draft" entry; the gate charges its params + draft KV pool
    # against the same hbm_gb budget (FF130)
    "draft", "spec_gamma", "spec_gamma_max", "spec_policy"))


@dataclasses.dataclass
class TenantSpec:
    """One fleet entry: everything needed to build, gate and serve a
    tenant.  ``builder(cfg) -> FFModel`` returns the UNCOMPILED graph;
    ``weight`` is the weighted-fair device-time share, ``qps_rows`` an
    optional rows/s budget (0 = unlimited; generation tenants budget
    requests/s — one row each)."""

    name: str
    builder: Callable
    engine: str = "dense"
    checkpoint: str = ""
    strategy: str = ""
    weight: float = 1.0
    qps_rows: float = 0.0
    batch_size: int = 0
    # "" = full precision; "int8" = weight-only quantized serving
    # (dense tenants only — FFModel.quantize_weights at engine warmup;
    # the co-residency gate accounts the int8 footprint byte-for-byte)
    quantize: str = ""
    # disaggregated-cluster role (TENANT_ROLES); only meaningful for
    # generation tenants — the router routes prompts to "prefill"/
    # "mixed" and migrates KV pages to "decode", and the FF132 gate
    # sizes decode pools / charges prefill staging bytes off this tag
    role: str = "mixed"
    serve: Dict = dataclasses.field(default_factory=dict)
    generation: Dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if "quantize" in self.serve:
            # quantize rides ONLY as the top-level TenantSpec field:
            # smuggled through the serve{} pass-through it would reach
            # the engine (cfg.serve_quantize) while the co-residency
            # gate — which keys on spec.quantize — still predicted f32
            # bytes, breaking the byte-for-byte pin
            raise ValueError(
                f"tenant {self.name!r}: put quantize at the tenant "
                f"level, not inside serve{{}}")
        if self.quantize not in ("", "int8"):
            raise ValueError(
                f"tenant {self.name!r}: quantize must be '' or 'int8', "
                f"got {self.quantize!r}")
        if self.quantize and self.engine != "dense":
            raise ValueError(
                f"tenant {self.name!r}: quantize applies to dense "
                f"tenants only (generation decode caches are not "
                f"weight-quantized)")
        if self.engine not in ENGINE_KINDS:
            raise ValueError(
                f"tenant {self.name!r}: engine must be one of "
                f"{ENGINE_KINDS}, got {self.engine!r}")
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name!r}: weight must be > 0, got "
                f"{self.weight}")
        if self.qps_rows < 0:
            raise ValueError(
                f"tenant {self.name!r}: qps_rows must be >= 0 "
                f"(0 = unlimited), got {self.qps_rows}")
        if self.role not in TENANT_ROLES:
            raise ValueError(
                f"tenant {self.name!r}: role must be one of "
                f"{TENANT_ROLES}, got {self.role!r}")
        if self.role != "mixed" and self.engine != "generation":
            raise ValueError(
                f"tenant {self.name!r}: role {self.role!r} applies to "
                f"generation tenants only (dense/draft tenants have no "
                f"prefill/decode split to disaggregate)")
        if self.engine == "draft" and (self.serve or self.generation):
            raise ValueError(
                f"tenant {self.name!r}: draft entries serve no traffic "
                f"of their own — no serve{{}}/generation{{}} sections "
                f"(the referencing tenant's generation section carries "
                f"the speculation knobs)")


def builtin_builders() -> Dict[str, Callable]:
    """The fleet's builtin graph registry: lint's model zoo plus the
    token-generation LM builders (causal decode graphs the
    GenerationEngine can serve)."""
    from ...cli import _lint_builders
    from ...models import build_lstm_lm, build_transformer_lm
    out = dict(_lint_builders())
    out["transformer_lm"] = lambda cfg: build_transformer_lm(
        cfg, num_layers=2, d_model=64, num_heads=4, d_ff=128,
        seq_len=64, vocab_size=128)[0]
    out["lstm_lm"] = lambda cfg: build_lstm_lm(cfg)[0]
    return out


def validate_fleet_json(obj) -> List[str]:
    """Schema problems of a fleet registry JSON (empty list = valid).
    THE one schema, shared by from_json, ``lint --fleet`` and the repo
    static gate."""
    probs: List[str] = []
    if not isinstance(obj, dict):
        return ["fleet file must be a JSON object"]
    fleet = obj.get("fleet")
    if not isinstance(fleet, list) or not fleet:
        return ["'fleet' must be a non-empty list of tenant entries"]
    if "hbm_gb" in obj and not isinstance(obj["hbm_gb"], (int, float)):
        probs.append("hbm_gb: want a number")
    # name -> engine kind pre-pass: generation.draft references another
    # entry IN THIS FILE, so the check needs the whole fleet first
    kinds = {e.get("name"): e.get("engine", "dense")
             for e in fleet if isinstance(e, dict)}
    seen = set()
    for i, e in enumerate(fleet):
        where = f"fleet[{i}]"
        if not isinstance(e, dict):
            probs.append(f"{where}: want an object")
            continue
        name = e.get("name")
        if not isinstance(name, str) or not name:
            probs.append(f"{where}: 'name' must be a non-empty string")
        elif name in seen:
            probs.append(f"{where}: duplicate tenant name {name!r}")
        else:
            seen.add(name)
        if not isinstance(e.get("model"), str) or not e.get("model"):
            probs.append(f"{where}: 'model' must name a builtin builder")
        kind = e.get("engine", "dense")
        if kind not in ENGINE_KINDS:
            probs.append(f"{where}: engine must be one of "
                         f"{', '.join(ENGINE_KINDS)}, got {kind!r}")
        role = e.get("role", "mixed")
        if role not in TENANT_ROLES:
            probs.append(f"{where}: role must be one of "
                         f"{', '.join(TENANT_ROLES)}, got {role!r}")
        elif role != "mixed" and kind != "generation":
            probs.append(f"{where}: role {role!r} applies to generation "
                         f"tenants only")
        for key, want in (("checkpoint", str), ("strategy", str)):
            if key in e and not isinstance(e[key], want):
                probs.append(f"{where}: {key} must be a string")
        if "quantize" in e and e["quantize"] not in ("", "int8"):
            probs.append(f"{where}: quantize must be '' or 'int8'")
        if e.get("quantize") and kind != "dense":
            probs.append(f"{where}: quantize applies to dense tenants "
                         f"only")
        for key in ("weight", "qps_rows"):
            if key in e and not isinstance(e[key], (int, float)):
                probs.append(f"{where}: {key} must be a number")
        if "weight" in e and isinstance(e["weight"], (int, float)) \
                and e["weight"] <= 0:
            probs.append(f"{where}: weight must be > 0")
        if "qps_rows" in e and isinstance(e["qps_rows"], (int, float)) \
                and e["qps_rows"] < 0:
            probs.append(f"{where}: qps_rows must be >= 0")
        if "batch_size" in e and not (isinstance(e["batch_size"], int)
                                      and e["batch_size"] >= 1):
            probs.append(f"{where}: batch_size must be an int >= 1")
        for section, allowed in (("serve", _SERVE_KEYS),
                                 ("generation", _GEN_KEYS)):
            sec = e.get(section)
            if sec is None:
                continue
            if not isinstance(sec, dict):
                probs.append(f"{where}: {section} must be an object")
                continue
            unknown = sorted(set(sec) - allowed)
            if unknown:
                probs.append(f"{where}: unknown {section} key(s) "
                             f"{unknown} (have {sorted(allowed)})")
            # paged-KV geometry keys: a negative value would flow into
            # the gate's kv_memory math as a NEGATIVE HBM charge
            for key in ("page_size", "num_pages", "prefill_chunk"):
                if key in sec and not (isinstance(sec[key], int)
                                       and sec[key] >= 0):
                    probs.append(f"{where}: {section}.{key} must be an "
                                 f"int >= 0 (0 = default/auto)")
            if section != "generation":
                continue
            # speculative-decoding knobs: the draft reference must
            # resolve INSIDE this file to an engine="draft" entry, or
            # the gate would charge a tenant the file never declares
            if "draft" in sec:
                d = sec["draft"]
                if not isinstance(d, str) or not d:
                    probs.append(f"{where}: generation.draft must name "
                                 f"a fleet entry")
                elif d not in kinds:
                    probs.append(f"{where}: generation.draft {d!r} is "
                                 f"not a fleet entry in this file")
                elif kinds[d] != "draft":
                    probs.append(f"{where}: generation.draft {d!r} "
                                 f"must have engine 'draft', has "
                                 f"{kinds[d]!r}")
            for key in ("spec_gamma", "spec_gamma_max"):
                if key in sec and not (isinstance(sec[key], int)
                                       and sec[key] >= 0):
                    probs.append(f"{where}: generation.{key} must be "
                                 f"an int >= 0")
            if "spec_gamma" in sec and isinstance(sec["spec_gamma"],
                                                  int) \
                    and sec["spec_gamma"] == 1:
                probs.append(f"{where}: generation.spec_gamma must be "
                             f"0 (off) or >= 2")
            if sec.get("spec_policy") is not None \
                    and sec["spec_policy"] not in ("fixed", "adaptive"):
                probs.append(f"{where}: generation.spec_policy must be "
                             f"'fixed' or 'adaptive'")
        if kind == "generation" and e.get("serve"):
            probs.append(f"{where}: generation tenants take a "
                         f"'generation' section, not 'serve'")
        if kind == "draft" and (e.get("serve") or e.get("generation")):
            probs.append(f"{where}: draft entries take no serve/"
                         f"generation sections (they serve no traffic "
                         f"of their own)")
    return probs


class ModelRegistry:
    """name → :class:`TenantSpec`.  The fleet engine builds serving
    tenants from it; the co-residency gate reads its device-free
    graphs."""

    def __init__(self):
        self._specs: Dict[str, TenantSpec] = {}
        self.hbm_gb: float = 0.0

    # ---- construction --------------------------------------------------
    def register(self, name: str, builder: Callable, **kw) -> TenantSpec:
        """Register (or replace — hot-swap re-registers) one tenant."""
        spec = TenantSpec(name=name, builder=builder, **kw)
        self._specs[name] = spec
        return spec

    @classmethod
    def from_json(cls, obj, builders: Optional[Dict] = None
                  ) -> "ModelRegistry":
        probs = validate_fleet_json(obj)
        if probs:
            raise ValueError("invalid fleet registry: "
                             + "; ".join(probs[:5]))
        builders = builders or builtin_builders()
        reg = cls()
        reg.hbm_gb = float(obj.get("hbm_gb", 0.0))
        for e in obj["fleet"]:
            if e["model"] not in builders:
                raise ValueError(
                    f"tenant {e['name']!r}: unknown model "
                    f"{e['model']!r} (have {', '.join(sorted(builders))})")
            reg.register(
                e["name"], builders[e["model"]],
                engine=e.get("engine", "dense"),
                checkpoint=e.get("checkpoint", ""),
                strategy=e.get("strategy", ""),
                weight=float(e.get("weight", 1.0)),
                qps_rows=float(e.get("qps_rows", 0.0)),
                batch_size=int(e.get("batch_size", 0)),
                quantize=str(e.get("quantize", "")),
                role=str(e.get("role", "mixed")),
                serve=dict(e.get("serve", {})),
                generation=dict(e.get("generation", {})))
        return reg

    @classmethod
    def from_file(cls, path: str, builders: Optional[Dict] = None
                  ) -> "ModelRegistry":
        with open(path) as f:
            obj = json.load(f)
        return cls.from_json(obj, builders)

    # ---- access --------------------------------------------------------
    def names(self) -> List[str]:
        return sorted(self._specs)

    def spec(self, name: str) -> TenantSpec:
        try:
            return self._specs[name]
        except KeyError:
            raise KeyError(f"no tenant {name!r} in the fleet registry "
                           f"(have {', '.join(self.names())})") from None

    def __contains__(self, name: str) -> bool:
        return name in self._specs

    def __len__(self) -> int:
        return len(self._specs)

    # ---- building ------------------------------------------------------
    def graph(self, name: str):
        """The tenant's UNCOMPILED graph + resolved strategies —
        device-free (no mesh, no tracing): what the co-residency gate
        lints.  Returns ``(model, strategies_or_None)``."""
        spec = self.spec(name)
        model = spec.builder(_tenant_config(spec))
        strategies = None
        if spec.strategy:
            from ...strategy.proto import load_strategy_file
            strategies = load_strategy_file(spec.strategy)
        return model, strategies

    def build(self, name: str, mesh=None):
        """Compile + initialize the tenant's model for serving (see
        :func:`build_model`)."""
        return build_model(self.spec(name), mesh=mesh)


def _tenant_config(spec: TenantSpec):
    from ...config import FFConfig
    cfg = FFConfig(compute_dtype="float32")
    if spec.batch_size:
        cfg.batch_size = spec.batch_size
    if spec.quantize:
        # the ServingEngine quantizes at warmup when this is set
        cfg.serve_quantize = spec.quantize
    for k, v in spec.serve.items():
        attr = "serve_" + k
        if hasattr(cfg, attr):
            setattr(cfg, attr, v)
    return cfg


def build_model(spec: TenantSpec, mesh=None):
    """Compile + initialize one tenant's model for serving: strategy
    ``.pb`` resolved into per-op configs (ffcheck-verified at compile),
    checkpoint restored when given.  This is the EXPENSIVE path — the
    fleet engine runs it on a background thread so a load/swap never
    stalls serving.  The ``fleet_load_fail:<name>`` FF_FAULT kind
    injects a deterministic build failure here."""
    from ... import faults
    for fspec in faults.fleet_faults():
        if fspec.kind == "fleet_load_fail" and fspec.arg == spec.name:
            raise RuntimeError(
                f"FF_FAULT: injected fleet load failure for "
                f"model {spec.name!r}")
    cfg = _tenant_config(spec)
    if spec.strategy:
        cfg.import_strategy_file = spec.strategy
    model = spec.builder(cfg)
    from ...optimizers import SGDOptimizer
    model.compile(SGDOptimizer(lr=0.01), mesh=mesh)
    model.init_layers(seed=cfg.seed)
    if spec.checkpoint:
        model.load_checkpoint(spec.checkpoint)
    return model


__all__ = ["ModelRegistry", "TenantSpec", "validate_fleet_json",
           "builtin_builders", "build_model", "ENGINE_KINDS",
           "TENANT_ROLES"]
