"""Int8 weight-only quantization for the serving bucket executables
(ISSUE 14; docs/serving.md "Int8 weight quantization").

Scheme: per-OUTPUT-channel symmetric quantization of the eligible
matmul kernels — for a ``(out, in)`` Linear kernel ``w``, each output
row ``c`` gets ``scale[c] = max|w[c, :]| / 127`` and
``q[c, :] = rint(w[c, :] / scale[c])`` in int8.  Because the scale is
per output channel, ``x @ (q * scale).T == (x @ q.T) * scale`` holds
EXACTLY, so the dequantization fuses into the matmul's epilogue
(``ops.common.dequant_matmul``) and the f32 weight never materializes:
the resident buffer is the int8 tensor plus a tiny f32 ``(out,)`` scale
vector — ~1/4 the HBM footprint and weight-streaming bandwidth of f32.

Quality bound: symmetric round-to-nearest guarantees
``|w - q * scale| <= scale / 2`` per channel, so the model-wide
``max_abs_err`` can never exceed ``max(scale) / 2``.  The measured
error and the bound are both in the report; the serving engine checks
``bound_ok`` at warmup and refuses to serve a violating table (the
check firing means the quantizer itself is broken — it is a tripwire,
not a tuning knob).

Eligibility (:func:`eligible_weights`) is THE one predicate, shared by
``FFModel.quantize_weights`` (the runtime) and the fleet co-residency
gate (``serving/fleet/gate.py`` — ``resident_bytes`` must predict the
engine's real allocation byte-for-byte): 2-D ``Linear`` kernels on the
device path.  Biases, norm scales, embeddings and conv filters stay in
their original dtype — kernels dominate serving residency, and the
per-output-channel scheme is exact only for the matmul contraction.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from ..ops.common import scale_param_name as scale_name

INT8_QMAX = 127

QUANT_MODES = ("", "int8")


def eligible_weights(layers) -> List[Tuple[Any, Any]]:
    """``[(op, weight), ...]`` of the kernels int8 quantization applies
    to: 2-D Linear matmul kernels.  Device-free (type/shape checks
    only), so the fleet gate sizes an uncompiled graph with the exact
    predicate the runtime quantizes by."""
    from ..ops.linear import Linear, host_placed
    out = []
    for op in layers:
        if not isinstance(op, Linear):
            continue
        if host_placed(getattr(op, "parallel_config", None)):
            # host-placed params keep the host-gather path; quantizing
            # them would change that contract for negligible HBM win
            continue
        w = getattr(op, "w_kernel", None)
        if w is not None and len(w.shape) == 2:
            out.append((op, w))
    return out


def eligible_weight_names(layers) -> frozenset:
    return frozenset(w.name for _, w in eligible_weights(layers))


def quantize_array(host: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                              float, float]:
    """Quantize one ``(out, in)`` f32 kernel: returns ``(q int8,
    scale f32 (out,), max_abs_err, error_bound)``.  Pure numpy — the
    same function the tests drive directly to pin the bound."""
    host = np.asarray(host, np.float32)
    amax = np.max(np.abs(host), axis=1) if host.size else np.zeros(
        host.shape[0], np.float32)
    # a zero row quantizes to zeros exactly; tiny floor avoids div-by-0
    scale = np.maximum(amax / INT8_QMAX,
                       np.finfo(np.float32).tiny).astype(np.float32)
    q = np.clip(np.rint(host / scale[:, None]),
                -INT8_QMAX, INT8_QMAX).astype(np.int8)
    if host.size:
        err = float(np.max(np.abs(host - q.astype(np.float32)
                                  * scale[:, None])))
        bound = float(np.max(scale)) * 0.5
    else:
        err = bound = 0.0
    # one-ulp headroom: the bound derivation is exact in real
    # arithmetic; float rounding of (q * scale) may add an ulp
    bound *= 1.0 + 1e-6
    return q, scale, err, bound


def quantize_params(model, mode: str = "int8"
                    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Quantized copy of ``model._params`` plus the quality report
    (``FFModel.quantize_weights`` is the caller — see its docstring for
    the placement/caching contract).  Eligible kernels are replaced by
    int8 arrays under the weight's existing sharding; their f32 scales
    ride replicated under ``scale_name(w)``."""
    import jax

    if mode != "int8":
        raise ValueError(f"unknown quantization mode {mode!r} "
                         f"(have {', '.join(m for m in QUANT_MODES if m)})")
    new_params: Dict[str, Any] = dict(model._params)
    rows: List[Dict] = []
    max_err = 0.0
    bound = 0.0
    bytes_before = bytes_after = 0
    repl_sharding = None
    if model.mesh is not None and model.mesh.is_distributed:
        import jax.sharding as jsh
        repl_sharding = model.mesh.sharding(jsh.PartitionSpec())
    for op, w in eligible_weights(model.layers):
        arr = model._params.get(w.name)
        if arr is None:
            continue
        host = np.asarray(jax.device_get(arr), np.float32)
        q, scale, err, wbound = quantize_array(host)
        sharding = getattr(arr, "sharding", None)
        q_arr = (jax.device_put(q, sharding) if sharding is not None
                 else jax.device_put(q))
        s_sh = repl_sharding if repl_sharding is not None else sharding
        s_arr = (jax.device_put(scale, s_sh) if s_sh is not None
                 else jax.device_put(scale))
        new_params[w.name] = q_arr
        new_params[scale_name(w.name)] = s_arr
        max_err = max(max_err, err)
        bound = max(bound, wbound)
        bytes_before += int(arr.nbytes)
        bytes_after += int(q.nbytes + scale.nbytes)
        rows.append({"op": op.name, "weight": w.name,
                     "shape": list(w.shape),
                     "scale_max": float(np.max(scale)) if scale.size
                     else 0.0,
                     "max_abs_err": err, "error_bound": wbound})
    report = {
        "mode": mode,
        "weights": rows,
        "max_abs_err": max_err,
        "error_bound": bound,
        "bound_ok": max_err <= bound or not rows,
        "bytes_before": bytes_before,
        "bytes_after": bytes_after,
    }
    return new_params, report


def quantized_params_bytes_delta(layers, strategies, mesh) -> float:
    """Per-device byte DELTA the int8 path applies on top of the f32
    ``static_params_bytes`` accounting (fleet gate): for every eligible
    kernel, the f32 shard (4 B/elem over its placement parts) is
    replaced by the int8 shard (1 B/elem, same parts) plus the
    REPLICATED f32 scale (out x 4 B on every device) — exactly what
    ``quantize_params`` places, so gate == engine byte-for-byte."""
    from ..parallel.sharding import param_spec
    from .fleet.gate import _subaxis_sizes
    sizes = _subaxis_sizes(mesh)
    delta = 0.0
    for op, w in eligible_weights(layers):
        pc = (strategies or {}).get(op.name)
        spec = param_spec(w, pc, mesh, on_fallback=lambda *a: None)
        parts = 1
        for entry in spec:
            if entry is None:
                continue
            names = (entry,) if isinstance(entry, str) else tuple(entry)
            for nm in names:
                parts *= sizes.get(nm, 1)
        vol = 1
        for s in w.shape:
            vol *= int(s)
        delta -= vol * 4.0 / parts          # the f32 shard leaves...
        delta += vol * 1.0 / parts          # ...the int8 shard arrives
        delta += int(w.shape[0]) * 4.0      # replicated (out,) scale
    return delta


__all__ = ["eligible_weights", "eligible_weight_names", "quantize_array",
           "quantize_params", "quantized_params_bytes_delta",
           "scale_name", "INT8_QMAX", "QUANT_MODES"]
