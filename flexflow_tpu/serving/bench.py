"""``serve-bench`` — serving-engine microbenchmark: shape-bucketed AOT
executables + dynamic micro-batching vs naive per-request ``predict()``.

Sibling of ``search-bench`` (search hot path) and ``train-bench``
(training dispatch amortization): this one measures the INFERENCE
request loop.  On a dispatch-bound configuration — a model small enough
that per-dispatch device compute is comparable to the per-dispatch host
cost — the engine wins twice: it coalesces many requests into one
device dispatch (one program, one ``device_get``, amortized over every
request in the packed batch) where the naive loop pays one dispatch +
one host sync per request, and it packs rows into right-sized shape
buckets where naive ``predict()`` pads every request to the one fixed
``batch_size``.

Three phases, all recorded in the JSON payload
(``artifacts/serve_bench_r*.json``):

1. **engine** — the synthetic request set submitted back-to-back
   (max-rate): rows/s and requests/s capacity, plus latency percentiles
   (backlogged, so queueing-dominated — capacity evidence, not an SLO);
2. **naive** — the same requests served serially via per-request
   ``predict()``: the baseline capacity and per-request service time;
3. **paced** — a Poisson (optionally bursty) arrival trace replayed
   open-loop against the engine at a rate derived from the measured
   capacity: the p50/p95/p99 a real client would see under load.

Run: ``python -m flexflow_tpu.cli serve-bench [--requests 512]
[--rows 1-8] [--max-batch 64] [--max-wait-ms 2] [--buckets 1,2,...]
[--burst 4] [--rate-frac 0.5] [--hidden 64] [--seed 0] [--out f.json]``
— JSON on stdout either way.  Fully measurable on CPU (the dispatch
overhead being amortized is exactly the part that needs no TPU).

``--overload`` switches to the OVERLOAD SWEEP (docs/serving.md
"Overload, SLOs & degradation"): measure capacity, then replay offered
load at ``--mults`` x capacity under each ``--policies`` admission
policy, reporting goodput (rows/s completed within the SLO) and
shed/expired/reject rates per cell
(``artifacts/serve_overload_r*.json``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Tuple

import numpy as np

NFEAT = 16
NCLS = 10


def _build_model(batch_size: int, hidden: int, seed: int,
                 max_batch: int, max_wait_ms: float, buckets: str):
    """Dispatch-bound small model (same shape class as train-bench):
    per-request device compute is ~10s of microseconds, so the request
    loop's host work dominates — the regime the engine amortizes."""
    import flexflow_tpu as ff
    from flexflow_tpu.parallel.mesh import MachineMesh

    cfg = ff.FFConfig(batch_size=batch_size, compute_dtype="float32",
                      seed=seed)
    cfg.serve_max_batch = max_batch
    cfg.serve_max_wait_ms = max_wait_ms
    cfg.serve_buckets = buckets
    m = ff.FFModel(cfg, mesh=MachineMesh({"n": 1}))
    x = m.create_tensor((batch_size, NFEAT), name="x")
    t = m.dense(x, hidden, activation="relu")
    t = m.dense(t, NCLS)
    m.compile(ff.SGDOptimizer(lr=0.05), metrics=["accuracy"])
    m.init_layers(seed=seed)
    return m


def make_requests(n: int, rows_lo: int, rows_hi: int, seed: int
                  ) -> List[np.ndarray]:
    """Synthetic request payloads with mixed row counts (uniform in
    [rows_lo, rows_hi]) — mixed sizes exercise every bucket."""
    rng = np.random.default_rng(seed)
    sizes = rng.integers(rows_lo, rows_hi + 1, n)
    return [rng.standard_normal((int(s), NFEAT)).astype(np.float32)
            for s in sizes]


def make_arrivals(n: int, rate: float, seed: int, burst: int = 1
                  ) -> np.ndarray:
    """Arrival offsets (seconds) for the paced phase: Poisson with mean
    ``rate`` requests/s; ``burst > 1`` clumps arrivals — bursts of
    ``burst`` simultaneous requests at Poisson burst times (same mean
    rate), the bursty half of the trace."""
    rng = np.random.default_rng(seed + 1)
    if burst <= 1:
        return np.cumsum(rng.exponential(1.0 / rate, n))
    nb = -(-n // burst)
    burst_t = np.cumsum(rng.exponential(burst / rate, nb))
    return np.repeat(burst_t, burst)[:n]


def _bitwise_parity(buckets) -> bool:
    """Whether engine-vs-predict checks may demand bit equality: the
    packing-invariance guarantee is validated on CPU with the default
    bucket set (tests/test_serving.py); an explicit bucket-1 list opts
    out (matrix-vector kernels, see derive_buckets), and other
    backends' matmul tiling may vary with batch shape — there the
    bench must still produce its payload, so it compares loosely."""
    import jax

    return 1 not in buckets and jax.default_backend() == "cpu"


def _run_engine_maxrate(model, reqs) -> Tuple[Dict, object]:
    """Phase 1: capacity — all requests submitted back-to-back."""
    from .engine import ServingEngine

    engine = ServingEngine(model)
    rows = sum(r.shape[0] for r in reqs)
    with engine:
        t0 = time.perf_counter()
        futs = [engine.submit(r) for r in reqs]
        outs = [f.result(timeout=120) for f in futs]
        dt = time.perf_counter() - t0
    snap = engine.stats()
    # spot-check: engine rows == the model's own predict on request 0
    # (>=2-row batch size: a 1-row predict would lower the
    # matrix-vector program the bucket design deliberately excludes)
    n0 = reqs[0].shape[0]
    want = model.predict(reqs[0], batch_size=max(2, n0))
    if _bitwise_parity(engine.buckets):
        np.testing.assert_array_equal(outs[0], want[:n0])
    else:
        np.testing.assert_allclose(outs[0], want[:n0], rtol=1e-5,
                                   atol=1e-6)
    return {
        "makespan_s": round(dt, 4),
        "qps_rows": round(rows / dt, 2),
        "qps_requests": round(len(reqs) / dt, 2),
        "p50_ms": snap["p50_ms"], "p95_ms": snap["p95_ms"],
        "p99_ms": snap["p99_ms"],
        "batch_occupancy": snap["batch_occupancy"],
        "dispatches": snap["dispatches"],
        "buckets": snap["buckets"],
    }, outs


def _run_naive(model, reqs) -> Tuple[Dict, object]:
    """Phase 2: the baseline — one ``predict()`` per request, each a
    full dispatch + host sync, padded to the model's fixed batch_size."""
    from flexflow_tpu.profiling import quantiles

    rows = sum(r.shape[0] for r in reqs)
    lat: List[float] = []
    outs = []
    t0 = time.perf_counter()
    for r in reqs:
        t1 = time.perf_counter()
        outs.append(model.predict(r))
        lat.append(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    q = quantiles(lat)
    return {
        "makespan_s": round(dt, 4),
        "qps_rows": round(rows / dt, 2),
        "qps_requests": round(len(reqs) / dt, 2),
        "p50_ms": round(q[0.5] * 1e3, 3),
        "p95_ms": round(q[0.95] * 1e3, 3),
        "p99_ms": round(q[0.99] * 1e3, 3),
    }, outs


def _run_paced(model, reqs, rate: float, burst: int, seed: int) -> Dict:
    """Phase 3: open-loop Poisson(+bursty) replay at ``rate`` req/s —
    the latency a client sees when the engine is NOT saturated."""
    from .engine import ServingEngine

    arrivals = make_arrivals(len(reqs), rate, seed, burst)
    engine = ServingEngine(model)
    with engine:
        t0 = time.perf_counter()
        futs = []
        for r, at in zip(reqs, arrivals):
            lag = t0 + at - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            futs.append(engine.submit(r))
        for f in futs:
            f.result(timeout=120)
    snap = engine.stats()
    return {
        "offered_rate_rps": round(rate, 2),
        "burst": burst,
        "p50_ms": snap["p50_ms"], "p95_ms": snap["p95_ms"],
        "p99_ms": snap["p99_ms"],
        "batch_occupancy": snap["batch_occupancy"],
        "dispatches": snap["dispatches"],
    }


# ----------------------------------------------------------------------
# overload sweep: offered load x admission policy -> goodput
# ----------------------------------------------------------------------
# the four load regimes the sweep compares (docs/serving.md "Overload,
# SLOs & degradation"): the unbounded-FIFO baseline (PR 5's fair-weather
# engine) vs the three admission policies with deadlines on
_OVERLOAD_POLICIES = {
    # name: (admission, bounded?, deadlines?)
    "fifo": ("block", False, False),
    "shed_oldest": ("shed_oldest", True, True),
    "reject": ("reject", True, True),
    "block": ("block", True, True),
}


def _run_overload_cell(model, reqs, rate: float, policy: str,
                       max_queue_rows: int, slo_ms: float, burst: int,
                       seed: int, device_kind: str,
                       calibration_digest) -> Dict:
    """One sweep cell: open-loop Poisson(+burst) replay at ``rate``
    req/s against a fresh engine configured for ``policy``, measuring
    GOODPUT — rows completed within the SLO — plus every way a request
    can fail (rejected / shed / expired / late), reconciled against the
    submitted count.  The same ``slo_ms`` judges every policy: the
    unbounded-FIFO baseline enforces no deadline, but its clients still
    stopped caring after slo_ms."""
    from ..profiling import quantiles
    from .engine import ServingEngine
    from .errors import OverloadError

    admission, bounded, deadlines = _OVERLOAD_POLICIES[policy]
    eng = ServingEngine(
        model, stats_every=0,
        max_queue_rows=max_queue_rows if bounded else 0,
        admission=admission)
    deadline_ms = slo_ms if deadlines else None
    arrivals = make_arrivals(len(reqs), rate, seed, burst)
    done: List[Dict] = []
    t0 = time.perf_counter()
    with eng:
        for r, at in zip(reqs, arrivals):
            lag = t0 + at - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            ts = time.perf_counter()
            try:
                fut = eng.submit(r, deadline_ms=deadline_ms)
            except OverloadError:
                continue  # counted engine-side (snap["rejected"])
            entry = {"rows": int(r.shape[0]), "t": ts, "t_done": None,
                     "ok": False}

            def cb(f, e=entry):
                e["t_done"] = time.perf_counter()
                e["ok"] = f.exception() is None and not f.cancelled()

            fut.add_done_callback(cb)
            done.append(entry)
        # bounded graceful shutdown: flush what is queued, then fail
        # stragglers — the drain verb under test, and what keeps the
        # collapsing-baseline cell from running unboundedly long
        eng.drain(timeout=max(1.0, 4 * slo_ms / 1e3))
    t_end = time.perf_counter()
    snap = eng.stats()
    completed = [e for e in done if e["ok"] and e["t_done"] is not None]
    lats = [(e["t_done"] - e["t"]) * 1e3 for e in completed]
    good = [e for e, l in zip(completed, lats) if l <= slo_ms]
    good_rows = sum(e["rows"] for e in good)
    elapsed = max(1e-6, t_end - t0)
    q = quantiles(lats)  # nearest-rank, unit-agnostic: these are ms

    def _ms(v):
        return None if v != v else round(v, 3)
    # every submitted request must be accounted for exactly once:
    # completed + rejected-at-submit + shed + expired + dispatch-errors
    reconciled = (snap["requests"] + snap["rejected"] + snap["shed"]
                  + snap["expired"] + snap["errors"]) == len(reqs)
    return {
        "policy": policy,
        "admission": admission,
        "deadline_ms": deadline_ms,
        "slo_ms": slo_ms,
        "max_queue_rows": max_queue_rows if bounded else 0,
        "offered_rps": round(rate, 2),
        "offered_requests": len(reqs),
        "offered_rows": int(sum(r.shape[0] for r in reqs)),
        "elapsed_s": round(elapsed, 4),
        "completed": len(completed),
        "good_requests": len(good),
        "good_rows": int(good_rows),
        "goodput_rows_per_s": round(good_rows / elapsed, 2),
        "rejected": snap["rejected"],
        "shed": snap["shed"],
        "expired": snap["expired"],
        "errors": snap["errors"],
        "late": len(completed) - len(good),
        "reconciled": bool(reconciled),
        "peak_queue_rows": snap["peak_queue_rows"],
        "admission_blocked_ms": snap["admission_blocked_ms"],
        "p50_ms": _ms(q[0.5]), "p95_ms": _ms(q[0.95]),
        "p99_ms": _ms(q[0.99]),
        # PR 7's row-stamping convention: every row carries enough
        # provenance to compare goodput trajectories across runs
        "device_kind": device_kind,
        "calibration_digest": calibration_digest,
    }


def run_overload_bench(requests: int = 512, rows_lo: int = 1,
                       rows_hi: int = 8, max_batch: int = 32,
                       max_wait_ms: float = 1.0, buckets: str = "",
                       hidden: int = 256, seed: int = 0, burst: int = 4,
                       cell_seconds: float = 2.0, slo_ms: float = 0.0,
                       queue_rows: int = 0,
                       mults=(0.5, 1.0, 2.0),
                       policies=("fifo", "shed_oldest", "reject", "block"),
                       calibration_digest=None) -> Dict:
    """The overload sweep: measure engine capacity, then replay offered
    load at ``mults`` x capacity under each admission policy, reporting
    goodput (rows/s completed within the SLO) and shed/expired/reject
    rates.  The acceptance shape (artifacts/serve_overload_r*.json): at
    2x offered load, ``shed_oldest`` + deadlines holds queue depth <=
    the bound and goodput >= 70% of its own 1x goodput, while the
    unbounded-FIFO baseline's queue and latency diverge."""
    import jax

    from ..search.calibration import device_kind as _device_kind

    model = _build_model(max_batch, hidden, seed, max_batch, max_wait_ms,
                         buckets)
    pool = make_requests(requests, rows_lo, rows_hi, seed)
    model.predict(pool[0])  # warm predict's bucket like serve-bench
    cap_row, _ = _run_engine_maxrate(model, pool)
    capacity_rps = cap_row["qps_requests"]
    mean_dispatch_ms = (cap_row["makespan_s"] / max(1, cap_row["dispatches"])
                        * 1e3)
    if slo_ms <= 0:
        # auto SLO: several dispatches' worth of wall time + the
        # coalescing wait — generous at 1x, hopeless for an unbounded
        # backlog at 2x
        slo_ms = max(25.0, 8 * mean_dispatch_ms + 2 * max_wait_ms)
    if queue_rows <= 0:
        queue_rows = 4 * max_batch
    dk = _device_kind()
    cells = []
    for ci, (policy, mult) in enumerate(
            (p, m) for p in policies for m in mults):
        rate = max(1.0, capacity_rps * mult)
        n = max(16, min(4096, int(rate * cell_seconds)))
        reqs = [pool[i % len(pool)] for i in range(n)]
        cell = _run_overload_cell(
            model, reqs, rate, policy, queue_rows, slo_ms, burst,
            seed + 13 * ci, dk, calibration_digest)
        cell["offered_mult"] = mult
        cells.append(cell)

    def _cell(policy, mult):
        # exact (policy, mult) match — a rate-ratio heuristic would
        # silently drop the acceptance summary on hosts slow enough
        # that the rate clamp distorts offered/capacity
        for c in cells:
            if c["policy"] == policy and c["offered_mult"] == mult:
                return c
        return None

    summary = {}
    shed1, shed2 = _cell("shed_oldest", 1.0), _cell("shed_oldest", 2.0)
    fifo2 = _cell("fifo", 2.0)
    if shed1 and shed2:
        summary["goodput_1x_shed_rows_per_s"] = shed1["goodput_rows_per_s"]
        summary["goodput_2x_shed_rows_per_s"] = shed2["goodput_rows_per_s"]
        summary["goodput_2x_over_1x_shed"] = round(
            shed2["goodput_rows_per_s"]
            / max(1e-6, shed1["goodput_rows_per_s"]), 3)
        summary["queue_bounded_at_2x"] = (
            shed2["peak_queue_rows"] <= queue_rows)
    if fifo2 and shed2:
        summary["goodput_2x_fifo_rows_per_s"] = fifo2["goodput_rows_per_s"]
        summary["fifo_2x_peak_queue_rows"] = fifo2["peak_queue_rows"]
    from ..analysis import comm_plan_digest_for_model
    return {
        "bench": "serve-overload",
        "backend": jax.default_backend(),
        "device_kind": dk,
        "precision_policy": model.config.precision_policy(),
        "comm_plan_digest": comm_plan_digest_for_model(model),
        "estimator": "measured",
        "config": {
            "requests_pool": requests, "rows": f"{rows_lo}-{rows_hi}",
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "hidden": hidden, "seed": seed, "burst": burst,
            "cell_seconds": cell_seconds, "slo_ms": round(slo_ms, 3),
            "max_queue_rows": queue_rows,
            "policies": list(policies), "mults": list(mults),
        },
        "capacity": {"qps_requests": capacity_rps,
                     "qps_rows": cap_row["qps_rows"],
                     "mean_dispatch_ms": round(mean_dispatch_ms, 3)},
        "cells": cells,
        "summary": summary,
        "calibration_digest": calibration_digest,
    }


def run_serve_bench(requests: int = 512, rows_lo: int = 1, rows_hi: int = 8,
                    max_batch: int = 64, max_wait_ms: float = 2.0,
                    buckets: str = "", hidden: int = 64, seed: int = 0,
                    burst: int = 4, rate_frac: float = 0.5,
                    paced_requests: int = 0, naive_requests: int = 0) -> Dict:
    """The full three-phase benchmark; returns the JSON payload.
    ``paced_requests``/``naive_requests`` default to sensible fractions
    of ``requests`` (the paced phase costs real wall-clock at the
    offered rate)."""
    import jax

    model = _build_model(max_batch, hidden, seed, max_batch, max_wait_ms,
                         buckets)
    reqs = make_requests(requests, rows_lo, rows_hi, seed)
    # warm: predict at the naive batch size (its one bucket), engine
    # buckets warm inside ServingEngine.__init__ via forward_compiled
    model.predict(reqs[0])

    # each capacity phase runs twice and the faster leg is kept — host
    # hiccups only ever inflate a wall-clock sample (same estimator
    # philosophy as bench.py's min-of-legs slope)
    engine_row, engine_outs = _run_engine_maxrate(model, reqs)
    engine_again, _ = _run_engine_maxrate(model, reqs)
    if engine_again["qps_rows"] > engine_row["qps_rows"]:
        engine_row = engine_again
    n_naive = naive_requests or min(requests, 256)
    naive_row, naive_outs = _run_naive(model, reqs[:n_naive])
    naive_again, _ = _run_naive(model, reqs[:n_naive])
    if naive_again["qps_rows"] > naive_row["qps_rows"]:
        naive_row = naive_again
    # parity across the two paths (bit-identical on CPU with the
    # default bucket set; bucket-1 opt-in or non-CPU backends compare
    # loosely — see _bitwise_parity)
    from .batcher import derive_buckets
    bitwise = _bitwise_parity(derive_buckets(max_batch, buckets))
    for got, want in zip(engine_outs[:8], naive_outs[:8]):
        if bitwise:
            np.testing.assert_array_equal(got, want)
        else:
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    n_paced = paced_requests or min(requests, 256)
    rate = max(1.0, engine_row["qps_requests"] * rate_frac)
    # keep the paced phase's wall-clock bounded (~4s) at any capacity
    n_paced = min(n_paced, int(rate * 4) + 1)
    paced_row = _run_paced(model, reqs[:n_paced], rate, burst, seed)

    from ..analysis import comm_plan_digest_for_model
    from ..search.calibration import device_kind as _device_kind
    return {
        "bench": "serve-bench",
        "backend": jax.default_backend(),
        "device_kind": _device_kind(),
        # the serving precision policy next to the provenance stamp
        # (ISSUE 14): int8-quantized and full-precision rows are
        # different populations
        "precision_policy": model.config.precision_policy(),
        # which sharding/communication plan served these rows (the
        # static plan digest from flexflow-tpu explain): rows measured
        # under different plans are different populations
        "comm_plan_digest": comm_plan_digest_for_model(model),
        "estimator": "measured",  # real engine run, not a sim estimate
        "config": {
            "requests": requests, "rows": f"{rows_lo}-{rows_hi}",
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "buckets": engine_row.pop("buckets"), "hidden": hidden,
            "naive_batch_size": model.config.batch_size, "seed": seed,
        },
        "engine": engine_row,
        "naive": naive_row,
        "paced": paced_row,
        "speedup_rows": round(
            engine_row["qps_rows"] / naive_row["qps_rows"], 2),
        "speedup_requests": round(
            engine_row["qps_requests"] / naive_row["qps_requests"], 2),
    }


_TRACE_COUNTERS = {
    "submitted": "ff_serve_submitted_total",
    "completed": "ff_serve_requests_total",
    "rejected": "ff_serve_rejected_total",
    "shed": "ff_serve_shed_total",
    "expired": "ff_serve_expired_total",
    "error": "ff_serve_errors_total",
    "cancelled": "ff_serve_cancelled_total",
}


def _registry_totals() -> Dict[str, int]:
    """Whole-process sums of the serving lifetime counters (all engine
    generations) — the baseline/endpoint of the trace reconciliation."""
    from ..obs.registry import get_registry
    fams = {f.name: f for f in get_registry().families()}
    return {k: int(fams[n].total()) if n in fams else 0
            for k, n in _TRACE_COUNTERS.items()}


def _finish_trace(tracer, path: str, counters0: Dict[str, int]) -> Dict:
    """Save the raw trace and reconcile it: every request submitted
    during the run must have produced exactly ONE terminal `request`
    span whose phase matches the engine counters
    (``submitted == completed+rejected+shed+expired+errors+cancelled``,
    per-phase equality).  The payload's `trace` section is the
    acceptance evidence; `sample_trace_ids` lets a reader pull those
    requests' full timelines out of the exported Chrome trace."""
    raw = tracer.save(path)
    phases = tracer.terminal_phase_counts()
    counters = {k: v - counters0.get(k, 0)
                for k, v in _registry_totals().items()}
    per_phase_ok = all(
        phases.get(ph, 0) == counters.get(ph, 0)
        for ph in ("completed", "rejected", "shed", "expired", "error",
                   "cancelled"))
    reconciled = (per_phase_ok
                  and sum(phases.values()) == counters["submitted"]
                  and raw.get("dropped", 0) == 0)
    sample_ids = [s["trace"] for s in raw["spans"]
                  if s["name"] == "request"][:4]
    tracer.disable()
    return {
        "file": path,
        "schema": raw["schema"],
        "spans": len(raw["spans"]),
        "dropped": raw.get("dropped", 0),
        "terminal_phases": phases,
        "counters": counters,
        "reconciled": bool(reconciled),
        "sample_trace_ids": sample_ids,
    }


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--generate" in argv:
        # token-generation benchmark: its own trace/flags
        # (docs/serving.md "Token generation")
        from .generation.bench import main as gen_main
        gen_main([a for a in argv if a != "--generate"])
        return
    if "--fleet" in argv:
        # multi-tenant isolation + hot-swap benchmark
        # (docs/serving.md "Model fleets")
        from .fleet.bench import main as fleet_main
        fleet_main([a for a in argv if a != "--fleet"])
        return
    if "--disagg" in argv:
        # disaggregated prefill/decode vs co-located chunked prefill
        # (docs/serving.md "Disaggregated prefill/decode")
        from .cluster.bench import main as disagg_main
        disagg_main([a for a in argv if a != "--disagg"])
        return
    ap = argparse.ArgumentParser(
        prog="flexflow-tpu serve-bench",
        description="serving-engine microbenchmark: shape-bucketed AOT "
                    "executables + micro-batching vs naive per-request "
                    "predict() (docs/serving.md)")
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--rows", default="1-8",
                    help="request row-count range, e.g. 1-8")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--buckets", default="",
                    help="explicit bucket list (default: powers of two)")
    ap.add_argument("--burst", type=int, default=4,
                    help="paced-phase burst size (1 = pure Poisson)")
    ap.add_argument("--rate-frac", type=float, default=0.5,
                    help="paced offered rate as a fraction of measured "
                         "engine capacity")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overload", action="store_true",
                    help="run the overload sweep instead of the "
                         "three-phase bench: offered load x admission "
                         "policy -> goodput (docs/serving.md "
                         "'Overload, SLOs & degradation')")
    ap.add_argument("--cell-seconds", type=float, default=2.0,
                    help="overload: offered-load duration per cell")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="overload: goodput SLO / per-request deadline "
                         "(0 = auto from measured dispatch time)")
    ap.add_argument("--queue-rows", type=int, default=0,
                    help="overload: serve_max_queue_rows for bounded "
                         "policies (0 = auto, 4x max-batch)")
    ap.add_argument("--mults", default="0.5,1,2",
                    help="overload: offered-load multiples of measured "
                         "capacity")
    ap.add_argument("--policies", default="fifo,shed_oldest,reject,block",
                    help="overload: admission policies to sweep")
    ap.add_argument("--calibration", default="",
                    help="CalibrationTable JSON whose digest the "
                         "payload records (comparability across "
                         "machines/calibration states; does not alter "
                         "the measured run)")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact here")
    ap.add_argument("--trace-out", default="",
                    help="enable span tracing at sample_rate=1.0 for "
                         "the whole run and write the raw ff-trace-v1 "
                         "file here (export with `flexflow-tpu trace "
                         "export`); the payload gains a `trace` section "
                         "reconciling terminal span counts EXACTLY "
                         "against the engine counters "
                         "(docs/observability.md)")
    ap.add_argument("--prom-out", default="",
                    help="write the process metrics registry's "
                         "Prometheus text exposition here after the "
                         "run (what GET /metrics would have served)")
    args = ap.parse_args(argv)
    try:
        lo, hi = (int(v) for v in args.rows.split("-"))
    except ValueError:
        ap.error(f"--rows wants LO-HI, got {args.rows!r}")
    if not (1 <= lo <= hi):
        ap.error(f"--rows wants 1 <= LO <= HI, got {args.rows!r}")
    # resolve the provenance digest BEFORE the measured run — a typo'd
    # --calibration must fail in milliseconds, not after the whole
    # engine/naive/paced sweep whose payload it would discard
    digest = None
    if args.calibration:
        from ..search.calibration import CalibrationTable
        try:
            digest = CalibrationTable.load(args.calibration).digest
        except (OSError, ValueError) as e:
            ap.error(f"cannot load --calibration {args.calibration!r}: {e}")

    tracer = None
    counters0 = {}
    if args.trace_out:
        from ..obs.trace import get_tracer
        tracer = get_tracer().configure(sample_rate=1.0,
                                        capacity=1 << 20)
        tracer.reset()
        counters0 = _registry_totals()
    # this bench's stdout IS the payload: silence the serve_stats /
    # epoch event streams while measuring (restored after)
    from ..fflogger import silenced
    with silenced("ff", "serve"):
        if args.overload:
            try:
                mults = tuple(float(v) for v in args.mults.split(",")
                              if v.strip())
                policies = tuple(p.strip() for p in
                                 args.policies.split(",") if p.strip())
            except ValueError:
                ap.error(f"bad --mults {args.mults!r}")
            unknown = [p for p in policies if p not in _OVERLOAD_POLICIES]
            if unknown:
                ap.error(f"unknown --policies {unknown} (have "
                         f"{', '.join(_OVERLOAD_POLICIES)})")
            payload = run_overload_bench(
                requests=args.requests, rows_lo=lo, rows_hi=hi,
                max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
                buckets=args.buckets, hidden=args.hidden,
                seed=args.seed, burst=args.burst,
                cell_seconds=args.cell_seconds, slo_ms=args.slo_ms,
                queue_rows=args.queue_rows, mults=mults,
                policies=policies, calibration_digest=digest)
        else:
            payload = run_serve_bench(
                requests=args.requests, rows_lo=lo, rows_hi=hi,
                max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
                buckets=args.buckets, hidden=args.hidden, seed=args.seed,
                burst=args.burst, rate_frac=args.rate_frac)
    payload["calibration_digest"] = digest
    if tracer is not None:
        payload["trace"] = _finish_trace(tracer, args.trace_out,
                                         counters0)
        print(f"# wrote {args.trace_out} "
              f"({payload['trace']['spans']} spans, reconciled="
              f"{payload['trace']['reconciled']})", file=sys.stderr)
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)
    if args.prom_out:
        from ..obs.registry import render_prometheus
        with open(args.prom_out, "w") as f:
            f.write(render_prometheus())
        print(f"# wrote {args.prom_out}", file=sys.stderr)


if __name__ == "__main__":
    main()
