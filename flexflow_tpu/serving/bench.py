"""``serve-bench`` — serving-engine microbenchmark: shape-bucketed AOT
executables + dynamic micro-batching vs naive per-request ``predict()``.

Sibling of ``search-bench`` (search hot path) and ``train-bench``
(training dispatch amortization): this one measures the INFERENCE
request loop.  On a dispatch-bound configuration — a model small enough
that per-dispatch device compute is comparable to the per-dispatch host
cost — the engine wins twice: it coalesces many requests into one
device dispatch (one program, one ``device_get``, amortized over every
request in the packed batch) where the naive loop pays one dispatch +
one host sync per request, and it packs rows into right-sized shape
buckets where naive ``predict()`` pads every request to the one fixed
``batch_size``.

Three phases, all recorded in the JSON payload
(``artifacts/serve_bench_r*.json``):

1. **engine** — the synthetic request set submitted back-to-back
   (max-rate): rows/s and requests/s capacity, plus latency percentiles
   (backlogged, so queueing-dominated — capacity evidence, not an SLO);
2. **naive** — the same requests served serially via per-request
   ``predict()``: the baseline capacity and per-request service time;
3. **paced** — a Poisson (optionally bursty) arrival trace replayed
   open-loop against the engine at a rate derived from the measured
   capacity: the p50/p95/p99 a real client would see under load.

Run: ``python -m flexflow_tpu.cli serve-bench [--requests 512]
[--rows 1-8] [--max-batch 64] [--max-wait-ms 2] [--buckets 1,2,...]
[--burst 4] [--rate-frac 0.5] [--hidden 64] [--seed 0] [--out f.json]``
— JSON on stdout either way.  Fully measurable on CPU (the dispatch
overhead being amortized is exactly the part that needs no TPU).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict, List, Tuple

import numpy as np

NFEAT = 16
NCLS = 10


def _build_model(batch_size: int, hidden: int, seed: int,
                 max_batch: int, max_wait_ms: float, buckets: str):
    """Dispatch-bound small model (same shape class as train-bench):
    per-request device compute is ~10s of microseconds, so the request
    loop's host work dominates — the regime the engine amortizes."""
    import flexflow_tpu as ff
    from flexflow_tpu.parallel.mesh import MachineMesh

    cfg = ff.FFConfig(batch_size=batch_size, compute_dtype="float32",
                      seed=seed)
    cfg.serve_max_batch = max_batch
    cfg.serve_max_wait_ms = max_wait_ms
    cfg.serve_buckets = buckets
    m = ff.FFModel(cfg, mesh=MachineMesh({"n": 1}))
    x = m.create_tensor((batch_size, NFEAT), name="x")
    t = m.dense(x, hidden, activation="relu")
    t = m.dense(t, NCLS)
    m.compile(ff.SGDOptimizer(lr=0.05), metrics=["accuracy"])
    m.init_layers(seed=seed)
    return m


def make_requests(n: int, rows_lo: int, rows_hi: int, seed: int
                  ) -> List[np.ndarray]:
    """Synthetic request payloads with mixed row counts (uniform in
    [rows_lo, rows_hi]) — mixed sizes exercise every bucket."""
    rng = np.random.default_rng(seed)
    sizes = rng.integers(rows_lo, rows_hi + 1, n)
    return [rng.standard_normal((int(s), NFEAT)).astype(np.float32)
            for s in sizes]


def make_arrivals(n: int, rate: float, seed: int, burst: int = 1
                  ) -> np.ndarray:
    """Arrival offsets (seconds) for the paced phase: Poisson with mean
    ``rate`` requests/s; ``burst > 1`` clumps arrivals — bursts of
    ``burst`` simultaneous requests at Poisson burst times (same mean
    rate), the bursty half of the trace."""
    rng = np.random.default_rng(seed + 1)
    if burst <= 1:
        return np.cumsum(rng.exponential(1.0 / rate, n))
    nb = -(-n // burst)
    burst_t = np.cumsum(rng.exponential(burst / rate, nb))
    return np.repeat(burst_t, burst)[:n]


def _bitwise_parity(buckets) -> bool:
    """Whether engine-vs-predict checks may demand bit equality: the
    packing-invariance guarantee is validated on CPU with the default
    bucket set (tests/test_serving.py); an explicit bucket-1 list opts
    out (matrix-vector kernels, see derive_buckets), and other
    backends' matmul tiling may vary with batch shape — there the
    bench must still produce its payload, so it compares loosely."""
    import jax

    return 1 not in buckets and jax.default_backend() == "cpu"


def _run_engine_maxrate(model, reqs) -> Tuple[Dict, object]:
    """Phase 1: capacity — all requests submitted back-to-back."""
    from .engine import ServingEngine

    engine = ServingEngine(model)
    rows = sum(r.shape[0] for r in reqs)
    with engine:
        t0 = time.perf_counter()
        futs = [engine.submit(r) for r in reqs]
        outs = [f.result(timeout=120) for f in futs]
        dt = time.perf_counter() - t0
    snap = engine.stats()
    # spot-check: engine rows == the model's own predict on request 0
    # (>=2-row batch size: a 1-row predict would lower the
    # matrix-vector program the bucket design deliberately excludes)
    n0 = reqs[0].shape[0]
    want = model.predict(reqs[0], batch_size=max(2, n0))
    if _bitwise_parity(engine.buckets):
        np.testing.assert_array_equal(outs[0], want[:n0])
    else:
        np.testing.assert_allclose(outs[0], want[:n0], rtol=1e-5,
                                   atol=1e-6)
    return {
        "makespan_s": round(dt, 4),
        "qps_rows": round(rows / dt, 2),
        "qps_requests": round(len(reqs) / dt, 2),
        "p50_ms": snap["p50_ms"], "p95_ms": snap["p95_ms"],
        "p99_ms": snap["p99_ms"],
        "batch_occupancy": snap["batch_occupancy"],
        "dispatches": snap["dispatches"],
        "buckets": snap["buckets"],
    }, outs


def _run_naive(model, reqs) -> Tuple[Dict, object]:
    """Phase 2: the baseline — one ``predict()`` per request, each a
    full dispatch + host sync, padded to the model's fixed batch_size."""
    from flexflow_tpu.profiling import quantiles

    rows = sum(r.shape[0] for r in reqs)
    lat: List[float] = []
    outs = []
    t0 = time.perf_counter()
    for r in reqs:
        t1 = time.perf_counter()
        outs.append(model.predict(r))
        lat.append(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    q = quantiles(lat)
    return {
        "makespan_s": round(dt, 4),
        "qps_rows": round(rows / dt, 2),
        "qps_requests": round(len(reqs) / dt, 2),
        "p50_ms": round(q[0.5] * 1e3, 3),
        "p95_ms": round(q[0.95] * 1e3, 3),
        "p99_ms": round(q[0.99] * 1e3, 3),
    }, outs


def _run_paced(model, reqs, rate: float, burst: int, seed: int) -> Dict:
    """Phase 3: open-loop Poisson(+bursty) replay at ``rate`` req/s —
    the latency a client sees when the engine is NOT saturated."""
    from .engine import ServingEngine

    arrivals = make_arrivals(len(reqs), rate, seed, burst)
    engine = ServingEngine(model)
    with engine:
        t0 = time.perf_counter()
        futs = []
        for r, at in zip(reqs, arrivals):
            lag = t0 + at - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
            futs.append(engine.submit(r))
        for f in futs:
            f.result(timeout=120)
    snap = engine.stats()
    return {
        "offered_rate_rps": round(rate, 2),
        "burst": burst,
        "p50_ms": snap["p50_ms"], "p95_ms": snap["p95_ms"],
        "p99_ms": snap["p99_ms"],
        "batch_occupancy": snap["batch_occupancy"],
        "dispatches": snap["dispatches"],
    }


def run_serve_bench(requests: int = 512, rows_lo: int = 1, rows_hi: int = 8,
                    max_batch: int = 64, max_wait_ms: float = 2.0,
                    buckets: str = "", hidden: int = 64, seed: int = 0,
                    burst: int = 4, rate_frac: float = 0.5,
                    paced_requests: int = 0, naive_requests: int = 0) -> Dict:
    """The full three-phase benchmark; returns the JSON payload.
    ``paced_requests``/``naive_requests`` default to sensible fractions
    of ``requests`` (the paced phase costs real wall-clock at the
    offered rate)."""
    import jax

    model = _build_model(max_batch, hidden, seed, max_batch, max_wait_ms,
                         buckets)
    reqs = make_requests(requests, rows_lo, rows_hi, seed)
    # warm: predict at the naive batch size (its one bucket), engine
    # buckets warm inside ServingEngine.__init__ via forward_compiled
    model.predict(reqs[0])

    # each capacity phase runs twice and the faster leg is kept — host
    # hiccups only ever inflate a wall-clock sample (same estimator
    # philosophy as bench.py's min-of-legs slope)
    engine_row, engine_outs = _run_engine_maxrate(model, reqs)
    engine_again, _ = _run_engine_maxrate(model, reqs)
    if engine_again["qps_rows"] > engine_row["qps_rows"]:
        engine_row = engine_again
    n_naive = naive_requests or min(requests, 256)
    naive_row, naive_outs = _run_naive(model, reqs[:n_naive])
    naive_again, _ = _run_naive(model, reqs[:n_naive])
    if naive_again["qps_rows"] > naive_row["qps_rows"]:
        naive_row = naive_again
    # parity across the two paths (bit-identical on CPU with the
    # default bucket set; bucket-1 opt-in or non-CPU backends compare
    # loosely — see _bitwise_parity)
    from .batcher import derive_buckets
    bitwise = _bitwise_parity(derive_buckets(max_batch, buckets))
    for got, want in zip(engine_outs[:8], naive_outs[:8]):
        if bitwise:
            np.testing.assert_array_equal(got, want)
        else:
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    n_paced = paced_requests or min(requests, 256)
    rate = max(1.0, engine_row["qps_requests"] * rate_frac)
    # keep the paced phase's wall-clock bounded (~4s) at any capacity
    n_paced = min(n_paced, int(rate * 4) + 1)
    paced_row = _run_paced(model, reqs[:n_paced], rate, burst, seed)

    from ..search.calibration import device_kind as _device_kind
    return {
        "bench": "serve-bench",
        "backend": jax.default_backend(),
        "device_kind": _device_kind(),
        "estimator": "measured",  # real engine run, not a sim estimate
        "config": {
            "requests": requests, "rows": f"{rows_lo}-{rows_hi}",
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "buckets": engine_row.pop("buckets"), "hidden": hidden,
            "naive_batch_size": model.config.batch_size, "seed": seed,
        },
        "engine": engine_row,
        "naive": naive_row,
        "paced": paced_row,
        "speedup_rows": round(
            engine_row["qps_rows"] / naive_row["qps_rows"], 2),
        "speedup_requests": round(
            engine_row["qps_requests"] / naive_row["qps_requests"], 2),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="flexflow-tpu serve-bench",
        description="serving-engine microbenchmark: shape-bucketed AOT "
                    "executables + micro-batching vs naive per-request "
                    "predict() (docs/serving.md)")
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--rows", default="1-8",
                    help="request row-count range, e.g. 1-8")
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--buckets", default="",
                    help="explicit bucket list (default: powers of two)")
    ap.add_argument("--burst", type=int, default=4,
                    help="paced-phase burst size (1 = pure Poisson)")
    ap.add_argument("--rate-frac", type=float, default=0.5,
                    help="paced offered rate as a fraction of measured "
                         "engine capacity")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--calibration", default="",
                    help="CalibrationTable JSON whose digest the "
                         "payload records (comparability across "
                         "machines/calibration states; does not alter "
                         "the measured run)")
    ap.add_argument("--out", default="",
                    help="also write the JSON artifact here")
    args = ap.parse_args(argv)
    try:
        lo, hi = (int(v) for v in args.rows.split("-"))
    except ValueError:
        ap.error(f"--rows wants LO-HI, got {args.rows!r}")
    if not (1 <= lo <= hi):
        ap.error(f"--rows wants 1 <= LO <= HI, got {args.rows!r}")
    # resolve the provenance digest BEFORE the measured run — a typo'd
    # --calibration must fail in milliseconds, not after the whole
    # engine/naive/paced sweep whose payload it would discard
    digest = None
    if args.calibration:
        from ..search.calibration import CalibrationTable
        try:
            digest = CalibrationTable.load(args.calibration).digest
        except (OSError, ValueError) as e:
            ap.error(f"cannot load --calibration {args.calibration!r}: {e}")

    # this bench's stdout IS the payload: silence the serve_stats /
    # epoch event streams while measuring (restored after)
    from ..fflogger import silenced
    with silenced("ff", "serve"):
        payload = run_serve_bench(
            requests=args.requests, rows_lo=lo, rows_hi=hi,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            buckets=args.buckets, hidden=args.hidden, seed=args.seed,
            burst=args.burst, rate_frac=args.rate_frac)
    payload["calibration_digest"] = digest
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
