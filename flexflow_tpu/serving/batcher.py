"""Dynamic micro-batcher — the request-coalescing half of the serving
engine (docs/serving.md).

Pure queueing logic, deliberately free of jax: requests enter a
thread-safe priority-class queue via :meth:`MicroBatcher.submit`; the
dispatcher pulls coalesced batches with :meth:`next_batch`, which
returns as soon as ``max_batch`` rows are pending OR the OLDEST pending
request has waited ``max_wait_ms`` (the latency floor under light load
— a lone request is never parked longer than the deadline waiting for
company).  Bucket selection (`bucket_for`) and oversize splitting
(`split_sizes`) are module-level pure functions so the boundary cases
pin down in unit tests without threads or devices.

Overload is a first-class regime (docs/serving.md "Overload, SLOs &
degradation"):

* the queue is BOUNDED (``max_queue_rows``; 0 = unbounded) and
  ``submit`` applies an admission policy when it is full — ``block``
  (wait for room), ``reject`` (raise :class:`~.errors.OverloadError`,
  nothing enqueued) or ``shed_oldest`` (evict the oldest queued request
  of the lowest priority class ≤ the incoming one, failing it with
  :class:`~.errors.SheddedError`).  ``block`` admission is
  deliberately unordered: woken producers race for freed room, so
  under sustained saturation a LARGE blocked request can be outrun
  indefinitely by smaller ones — callers needing bounded admission
  latency under overload should prefer ``reject``/``shed_oldest``
  (+ deadlines), which is what the overload sweep recommends;
* requests carry an optional absolute ``deadline``: queued work whose
  deadline has passed is expired BEFORE packing (its ``on_done`` fires
  with :class:`~.errors.DeadlineExceeded`) so a dead request never
  burns a device dispatch;
* requests carry an integer ``priority`` class (higher = served
  first); coalescing prefers higher classes while preserving FIFO
  within a class, and a starving class — oldest request waiting ≥
  ``starvation_ms`` — jumps the priority order (aging bound: low
  priority means "later", never "never").

With the defaults (unbounded queue, no deadlines, one priority class)
every path above is skipped and the batcher is the exact FIFO it was
before overload handling existed — the un-overloaded engine stays
bit-identical.

The wall clock is injectable (``clock=``) — the deadline/overload tests
drive a fake clock through `poll()` instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import lockwatch
from .errors import DeadlineExceeded, OverloadError, SheddedError

ADMISSION_POLICIES = ("block", "reject", "shed_oldest")


def derive_buckets(max_batch: int, spec: str = "") -> Tuple[int, ...]:
    """The engine's shape buckets: ``spec`` ("2,4,16,64") when given,
    else powers of two ``2, 4, ..., max_batch``.  Always sorted,
    deduplicated, and CLOSED under the engine's needs: ``max_batch``
    itself is always a bucket (every coalesced batch has a covering
    bucket), and every bucket is <= ``max_batch``.

    The default set starts at 2, not 1: a single-row program lowers to
    a matrix-VECTOR kernel whose accumulation order differs from the
    matrix-matrix path by ~1 ulp, so a bucket-1 dispatch would break
    packing-invariance (the same request returning different bits
    depending on whether the batcher coalesced it with neighbors —
    tests/test_serving.py pins engine == predict bit-identically).  A
    lone 1-row request pads one row into bucket 2; bucket 1 remains
    available explicitly via ``spec`` for callers that prefer the
    smaller program over bitwise packing-invariance."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if spec:
        try:
            buckets = sorted({int(v) for v in spec.split(",") if v.strip()})
        except ValueError:
            raise ValueError(f"bad bucket spec {spec!r} (want e.g. "
                             f"'2,4,16,64')")
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {spec!r}")
        if buckets[-1] > max_batch:
            raise ValueError(f"bucket {buckets[-1]} exceeds max_batch "
                             f"{max_batch}")
    else:
        buckets, b = [], 2
        while b < max_batch:
            buckets.append(b)
            b *= 2
    if not buckets or buckets[-1] != max_batch:
        buckets.append(max_batch)
    return tuple(buckets)


def bucket_for(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket covering ``n`` rows; None when ``n`` exceeds the
    largest bucket (the caller splits first — `split_sizes`)."""
    for b in buckets:
        if b >= n:
            return b
    return None


def split_sizes(n: int, max_batch: int) -> List[int]:
    """Chunk row counts for an oversize request: ``max_batch``-row
    chunks plus the remainder (order preserved — the engine reassembles
    chunk outputs by offset)."""
    if n <= max_batch:
        return [n]
    sizes = [max_batch] * (n // max_batch)
    if n % max_batch:
        sizes.append(n % max_batch)
    return sizes


class Request:
    """One queued unit of work: ``xs`` is a tuple of per-input row
    blocks (all leading dim ``n``); ``on_done(outputs, now)`` fires on
    the dispatcher thread once the packed batch containing this request
    has been fetched (`outputs` is this request's row slice, or an
    exception on the dispatch error / expiry / shed path) and returns
    True iff this call completed the LOGICAL request's future (split
    chunks share one — the error accounting counts completions, not
    chunks).

    ``deadline`` is an ABSOLUTE clock() time after which the request is
    expired instead of packed (None = no deadline); ``priority`` is the
    admission class (higher = served first; default 0); ``stale`` is an
    optional zero-arg predicate — True means the logical request is
    already resolved (a sibling chunk expired/failed, or the client
    cancelled) and this entry is dropped silently at the next scan
    instead of burning dispatch rows; ``trace`` is the request's
    sampled trace id (obs.trace) or None — the batcher never reads it,
    the dispatcher stamps its ``queue`` span with it."""

    __slots__ = ("xs", "n", "on_done", "t_submit", "deadline", "priority",
                 "stale", "trace")

    def __init__(self, xs, n: int, on_done, t_submit: float,
                 deadline: Optional[float] = None, priority: int = 0,
                 stale: Optional[Callable[[], bool]] = None,
                 trace: Optional[str] = None):
        self.xs = xs
        self.n = n
        self.on_done = on_done
        self.t_submit = t_submit
        self.deadline = deadline
        self.priority = int(priority)
        self.stale = stale
        self.trace = trace

    @property
    def _watched(self) -> bool:
        return self.deadline is not None or self.stale is not None


class MicroBatcher:
    """Thread-safe coalescing queue between `submit()` callers and the
    single dispatcher thread, with bounded-queue admission control."""

    def __init__(self, max_batch: int, max_wait_ms: float,
                 clock: Callable[[], float] = time.monotonic,
                 max_queue_rows: int = 0, admission: str = "block",
                 starvation_ms: float = 0.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if admission not in ADMISSION_POLICIES:
            raise ValueError(
                f"unknown admission policy {admission!r} "
                f"(want one of {', '.join(ADMISSION_POLICIES)})")
        if 0 < max_queue_rows < max_batch:
            raise ValueError(
                f"max_queue_rows {max_queue_rows} < max_batch {max_batch}: "
                f"a full batch could never queue")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.admission = admission
        self.starvation_s = float(starvation_ms) / 1e3
        self.clock = clock
        self._cv = lockwatch.condition("MicroBatcher._cv")
        # priority class -> FIFO deque (ONE class 0 deque in the default
        # path — identical semantics to the plain FIFO this replaced)
        self._classes: Dict[int, deque] = {}  # guarded_by: self._cv
        self._rows = 0        # guarded_by: self._cv
        self._count = 0       # guarded_by: self._cv
        self._watch = 0       # guarded_by: self._cv
        self._peak_rows = 0   # guarded_by: self._cv
        # the absolute time the dispatcher's current cv.wait will
        # self-expire, while it is parked in next_batch (-inf while it
        # is awake or absent): submit only needs to wake it for an
        # incoming DEADLINE that precedes this — notifying on every
        # deadlined submit would re-introduce the per-submit GIL
        # ping-pong the state-change-only notify below exists to avoid
        self._armed_wake = float("-inf")  # guarded_by: self._cv
        self._closed = False  # guarded_by: self._cv

    # ---- producer side -------------------------------------------------
    def submit(self, req: Request) -> float:
        return self.submit_all((req,))

    def submit_all(self, reqs: Sequence[Request],
                   admission: Optional[str] = None) -> float:
        """Enqueue ``reqs`` atomically: either every request is
        accepted or none is (closed batcher, rejected/unsheddable
        overload) — the chunks of one split oversize request must never
        half-enqueue around a concurrent close() or a full queue, which
        would drain orphan chunks whose join future the caller never
        received.

        Applies the admission policy when the queue bound is set
        (``admission=`` overrides the instance policy — the engine's
        fault-injected queue spikes must never self-deadlock blocking
        on the dispatcher thread).  Returns the seconds spent blocked
        for admission (0.0 except under ``block`` on a full queue)."""
        if not reqs:
            return 0.0  # uniform no-op across policies (shed_oldest
            #             would otherwise min() over an empty sequence)
        total = 0
        for req in reqs:
            if req.n > self.max_batch:
                raise ValueError(
                    f"request of {req.n} rows exceeds max_batch "
                    f"{self.max_batch}; split first (split_sizes)")
            total += req.n
        policy = admission or self.admission
        blocked_s = 0.0
        shed: List[Request] = []
        overload: Optional[OverloadError] = None
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self.max_queue_rows > 0:
                if total > self.max_queue_rows:
                    raise OverloadError(
                        f"request of {total} rows exceeds the queue bound "
                        f"serve_max_queue_rows={self.max_queue_rows}")
                if policy == "block":
                    t0 = self.clock()
                    while (self._rows + total > self.max_queue_rows
                           and not self._closed):
                        self._cv.wait()
                    blocked_s = self.clock() - t0
                    if self._closed:
                        raise RuntimeError("batcher is closed")
                elif policy == "reject":
                    if self._rows + total > self.max_queue_rows:
                        overload = OverloadError(
                            f"queue full ({self._rows} rows pending, "
                            f"bound {self.max_queue_rows}): request of "
                            f"{total} rows rejected")
                elif policy == "shed_oldest":
                    shed = self._evict_for(
                        total, min(r.priority for r in reqs))
                    if self._rows + total > self.max_queue_rows:
                        overload = OverloadError(
                            f"queue full of higher-priority work "
                            f"({self._rows} rows pending, bound "
                            f"{self.max_queue_rows}): request of {total} "
                            f"rows not admitted")
            if overload is None:
                was_rows = self._rows
                was_empty = self._count == 0
                for req in reqs:
                    self._classes.setdefault(req.priority,
                                             deque()).append(req)
                    self._rows += req.n
                    self._count += 1
                    if req._watched:
                        self._watch += 1
                self._peak_rows = max(self._peak_rows, self._rows)
                # wake the dispatcher only on a state change it must act
                # on: the queue turning nonempty (a deadline now needs
                # arming), the batch turning full (dispatch now), or a
                # request deadline that precedes the wake it is parked
                # on (computed before this deadline existed — without a
                # wake, expiry would fire up to max_wait late instead
                # of AT the deadline).  Notifying every submit would
                # wake it dozens of times per batch just to re-sleep —
                # measured ~3x engine throughput lost to the GIL
                # ping-pong under a hot submit loop.  notify_all, not
                # notify: producers blocked for admission share this
                # condition, and a lone notify could wake one of THEM
                # instead of the dispatcher.
                if (was_empty or was_rows < self.max_batch <= self._rows
                        or any(r.deadline is not None
                               and r.deadline < self._armed_wake
                               for r in reqs)):
                    self._cv.notify_all()
        # fire shed callbacks OUTSIDE the lock: a future callback may
        # re-enter submit(), and the condition's lock is not re-entrant
        if shed:
            now = self.clock()
            for r in shed:
                r.on_done(SheddedError(
                    f"shed after queueing {now - r.t_submit:.3f}s to admit "
                    f"newer work (shed_oldest, bound "
                    f"{self.max_queue_rows} rows)"), now)
        if overload is not None:
            raise overload
        return blocked_s

    def _evict_for(self, need_rows: int,  # guarded_by: self._cv
                   incoming_priority: int) -> List[Request]:
        """shed_oldest eviction (lock held): pop the oldest request of
        the LOWEST priority class not above the incoming request's —
        shedding never displaces strictly higher-priority work — until
        ``need_rows`` fit.  Evicts NOTHING when even shedding every
        eligible victim could not make room (the higher-priority
        remainder still overflows): the incoming request is refused
        either way, and killing queued work for a request that cannot
        be admitted would be pure loss.  Returns the victims; the
        caller fails them outside the lock."""
        eligible = sum(r.n for p, dq in self._classes.items()
                       if p <= incoming_priority for r in dq)
        if self._rows - eligible + need_rows > self.max_queue_rows:
            return []
        out: List[Request] = []
        while self._rows + need_rows > self.max_queue_rows:
            victim_cls = min(
                (p for p, dq in self._classes.items()
                 if dq and p <= incoming_priority), default=None)
            if victim_cls is None:
                break
            r = self._classes[victim_cls].popleft()
            if not self._classes[victim_cls]:
                del self._classes[victim_cls]
            self._unlink(r)
            out.append(r)
        return out

    def close(self) -> None:
        """Stop accepting work; `next_batch` drains what is pending and
        then returns None.  Producers blocked for admission are woken
        and fail with the closed error."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def requeue(self, reqs: Sequence[Request]) -> None:
        """Transfer already-admitted requests INTO this batcher,
        bypassing admission: the hot-swap path moves the outgoing
        engine's pending queue to its replacement at the publish
        boundary (serving/fleet), and work that was admitted once must
        not be re-judged — re-rejecting it would turn a zero-loss swap
        into shed requests.  Order: requeued requests keep their
        original submit times, and within a priority class they land
        ahead of anything the new engine queued meanwhile only if
        requeued first (the fleet publishes before re-opening
        admission, so in practice they do)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            was_empty = self._count == 0
            for req in reqs:
                self._classes.setdefault(req.priority,
                                         deque()).append(req)
                self._rows += req.n
                self._count += 1
                if req._watched:
                    self._watch += 1
            self._peak_rows = max(self._peak_rows, self._rows)
            if reqs and (was_empty or self._rows >= self.max_batch):
                self._cv.notify_all()

    def fail_pending(self) -> List[Request]:
        """Atomically remove EVERYTHING still queued and hand it to the
        caller (drain-timeout stragglers: the engine fails their
        futures).  The queue is empty afterwards; callbacks are the
        caller's job — outside any lock."""
        with self._cv:
            out: List[Request] = []
            for dq in self._classes.values():
                out.extend(dq)
            self._classes.clear()
            self._rows = 0
            self._count = 0
            self._watch = 0
            self._cv.notify_all()
        out.sort(key=lambda r: r.t_submit)
        return out

    # ---- consumer side -------------------------------------------------
    @property
    def queue_depth(self) -> int:
        """Pending requests (live snapshot, for metrics)."""
        with self._cv:
            return self._count

    @property
    def pending_rows(self) -> int:
        with self._cv:
            return self._rows

    @property
    def peak_rows(self) -> int:
        """High-water mark of queued rows over the batcher's lifetime —
        the bounded-queue evidence serve-bench's overload sweep records
        (must stay <= max_queue_rows when the bound is set)."""
        with self._cv:
            return self._peak_rows

    def _unlink(self, r: Request) -> None:  # guarded_by: self._cv
        """Accounting for a request leaving the queue (lock held)."""
        self._rows -= r.n
        self._count -= 1
        if r._watched:
            self._watch -= 1

    def _oldest_t(self) -> Optional[float]:  # guarded_by: self._cv
        """Submit time of the oldest queued request (lock held) — class
        heads are each class's oldest, so the min over heads is global."""
        return min((dq[0].t_submit for dq in self._classes.values() if dq),
                   default=None)

    def _ready(self, now: float) -> bool:  # guarded_by: self._cv
        if not self._count:
            return False
        if self._rows >= self.max_batch:
            return True
        oldest = self._oldest_t()
        return oldest is not None and now - oldest >= self.max_wait_s

    def _collect_expired(self, now: float  # guarded_by: self._cv
                         ) -> List[Request]:
        """Remove deadline-expired and stale requests (lock held) and
        return the EXPIRED ones — the caller fires their ``on_done``
        with DeadlineExceeded outside the lock.  Stale entries (logical
        request already resolved — sibling chunk expired/failed, or
        client cancel) are dropped silently: their future is done, and
        dropping them here is what makes split-request expiry atomic
        (no surviving chunk burns a dispatch).  Skipped entirely when
        nothing queued carries a deadline or stale predicate — the
        default path never pays the scan."""
        if not self._watch:
            return []
        fire: List[Request] = []
        freed = False
        for p in list(self._classes):
            dq = self._classes[p]
            dead = []
            for r in dq:
                stale = r.stale is not None and r.stale()
                expired = r.deadline is not None and now >= r.deadline
                if stale or expired:
                    dead.append((r, expired and not stale))
            if not dead:
                # the common wake: nothing to remove — never rebuild a
                # deque just to look (a deep queue with one live
                # deadline would otherwise be copied on every wake)
                continue
            gone = {id(r) for r, _ in dead}
            keep: deque = deque(r for r in dq if id(r) not in gone)
            for r, do_fire in dead:
                self._unlink(r)
                if do_fire:
                    fire.append(r)
            freed = True
            if keep:
                self._classes[p] = keep
            else:
                del self._classes[p]
        if freed:
            self._cv.notify_all()  # room for blocked producers
        return fire

    def _fire_expired(self, fire: List[Request]) -> None:
        if not fire:
            return
        now = self.clock()
        for r in fire:
            r.on_done(DeadlineExceeded(
                f"deadline passed {now - r.deadline:.3f}s ago while "
                f"queued (waited {now - r.t_submit:.3f}s; expired before "
                f"packing, no dispatch burned)"), now)

    def _class_order(self, now: float) -> List[int]:  # guarded_by: self._cv
        """Service order over priority classes (lock held): higher
        class first, EXCEPT that starving classes — oldest request
        waiting >= starvation_ms — jump ahead, oldest-first.  The aging
        bound keeps low-priority latency bounded under sustained
        high-priority load: "low priority" means later, never never."""
        classes = [p for p, dq in self._classes.items() if dq]
        if len(classes) <= 1:
            return classes
        starving = []
        if self.starvation_s > 0:
            starving = [p for p in classes
                        if now - self._classes[p][0].t_submit
                        >= self.starvation_s]
            starving.sort(key=lambda p: self._classes[p][0].t_submit)
        rest = sorted((p for p in classes if p not in starving),
                      reverse=True)
        return starving + rest

    def _take(self, now: float) -> List[Request]:  # guarded_by: self._cv
        """Pop a coalesced batch of at most ``max_batch`` rows (lock
        held): classes in `_class_order`, a FIFO prefix within each
        class (whole requests only — order-preserving, and the scatter
        stays one contiguous slice per request); oversize requests were
        already split at submit.  With one class this is exactly the
        old FIFO-prefix pop."""
        out: List[Request] = []
        rows = 0
        for p in self._class_order(now):
            dq = self._classes[p]
            while dq and rows + dq[0].n <= self.max_batch:
                r = dq.popleft()
                self._unlink(r)
                rows += r.n
                out.append(r)
            if not dq:
                del self._classes[p]
            if rows >= self.max_batch:
                break
        if out:
            self._cv.notify_all()  # room for blocked producers
        return out

    def reap_expired(self) -> int:
        """Expire deadline-passed / stale queued requests NOW without
        popping a batch.  Consumers whose take cadence is not their
        expiry cadence call this at their own boundaries — the
        generation engine's decode loop can run with every slot busy
        for seconds while queued prompts' deadlines lapse, and poll()
        (which would also TAKE work) only runs when a slot frees.
        Returns the number of requests expired.

        The no-deadline case is O(1): ``_watch`` is the live count of
        deadline/stale-bearing requests, and when it is zero this
        returns without reading the clock or entering the scan at all
        — this runs at EVERY decode-step boundary, and the common
        workload queues nothing reapable (pinned in
        tests/test_serving.py: the scan path is never entered)."""
        with self._cv:
            if not self._watch:
                return 0
            fire = self._collect_expired(self.clock())
        self._fire_expired(fire)
        return len(fire)

    def poll(self) -> Optional[List[Request]]:
        """Non-blocking `next_batch`: a coalesced batch if one is due
        (full, past the deadline, or draining after close), else None.
        Expires dead requests first — the fake-clock overload tests
        drive the whole deadline/admission matrix through this."""
        while True:
            with self._cv:
                now = self.clock()
                fire = self._collect_expired(now)
                batch = None
                if not fire and self._count and (self._closed
                                                 or self._ready(now)):
                    batch = self._take(now)
            if not fire:
                return batch
            self._fire_expired(fire)

    def _wake_in(self, now: float) -> Optional[float]:  # guarded_by: self._cv
        """Seconds until the next self-scheduled event (lock held):
        the oldest request's flush deadline, and — when deadlines are
        queued — the earliest expiry (an expired future must fail at
        its deadline, not whenever the next flush happens to look)."""
        wait = None
        oldest = self._oldest_t()
        if oldest is not None:
            wait = oldest + self.max_wait_s - now
        if self._watch:
            ed = min((r.deadline for dq in self._classes.values()
                      for r in dq if r.deadline is not None), default=None)
            if ed is not None:
                wait = ed - now if wait is None else min(wait, ed - now)
        return wait

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[Request]]:
        """Block until a batch is due, the batcher is closed AND
        drained (returns None — dispatcher exits), or ``timeout``
        expires (returns None; caller re-checks its stop flag)."""
        deadline = None if timeout is None else self.clock() + timeout
        while True:
            with self._cv:
                now = self.clock()
                fire = self._collect_expired(now)
                if not fire:
                    if self._count and (self._closed or self._ready(now)):
                        return self._take(now)
                    if self._closed and not self._count:
                        return None
                    # sleep until the oldest request's flush deadline /
                    # earliest expiry (or the caller's timeout / a
                    # submit notification)
                    wait = self._wake_in(now)
                    if deadline is not None:
                        if now >= deadline:
                            return None
                        wait = (deadline - now if wait is None
                                else min(wait, deadline - now))
                    # publish when this wait self-expires so submit()
                    # can tell whether an incoming deadline needs a
                    # wake; -inf while awake (it recomputes anyway)
                    self._armed_wake = (float("inf") if wait is None
                                        else now + max(0.0, wait))
                    self._cv.wait(None if wait is None
                                  else max(0.0, wait))
                    self._armed_wake = float("-inf")
                    continue
            self._fire_expired(fire)
