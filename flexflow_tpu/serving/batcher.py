"""Dynamic micro-batcher — the request-coalescing half of the serving
engine (docs/serving.md).

Pure queueing logic, deliberately free of jax: requests enter a
thread-safe FIFO via :meth:`MicroBatcher.submit`; the dispatcher pulls
coalesced batches with :meth:`next_batch`, which returns as soon as
``max_batch`` rows are pending OR the OLDEST pending request has waited
``max_wait_ms`` (the latency floor under light load — a lone request is
never parked longer than the deadline waiting for company).  Bucket
selection (`bucket_for`) and oversize splitting (`split_sizes`) are
module-level pure functions so the boundary cases pin down in unit
tests without threads or devices.

The wall clock is injectable (``clock=``) — the deadline-flush tests
drive a fake clock through `poll()` instead of sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple


def derive_buckets(max_batch: int, spec: str = "") -> Tuple[int, ...]:
    """The engine's shape buckets: ``spec`` ("2,4,16,64") when given,
    else powers of two ``2, 4, ..., max_batch``.  Always sorted,
    deduplicated, and CLOSED under the engine's needs: ``max_batch``
    itself is always a bucket (every coalesced batch has a covering
    bucket), and every bucket is <= ``max_batch``.

    The default set starts at 2, not 1: a single-row program lowers to
    a matrix-VECTOR kernel whose accumulation order differs from the
    matrix-matrix path by ~1 ulp, so a bucket-1 dispatch would break
    packing-invariance (the same request returning different bits
    depending on whether the batcher coalesced it with neighbors —
    tests/test_serving.py pins engine == predict bit-identically).  A
    lone 1-row request pads one row into bucket 2; bucket 1 remains
    available explicitly via ``spec`` for callers that prefer the
    smaller program over bitwise packing-invariance."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if spec:
        try:
            buckets = sorted({int(v) for v in spec.split(",") if v.strip()})
        except ValueError:
            raise ValueError(f"bad bucket spec {spec!r} (want e.g. "
                             f"'2,4,16,64')")
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {spec!r}")
        if buckets[-1] > max_batch:
            raise ValueError(f"bucket {buckets[-1]} exceeds max_batch "
                             f"{max_batch}")
    else:
        buckets, b = [], 2
        while b < max_batch:
            buckets.append(b)
            b *= 2
    if not buckets or buckets[-1] != max_batch:
        buckets.append(max_batch)
    return tuple(buckets)


def bucket_for(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket covering ``n`` rows; None when ``n`` exceeds the
    largest bucket (the caller splits first — `split_sizes`)."""
    for b in buckets:
        if b >= n:
            return b
    return None


def split_sizes(n: int, max_batch: int) -> List[int]:
    """Chunk row counts for an oversize request: ``max_batch``-row
    chunks plus the remainder (order preserved — the engine reassembles
    chunk outputs by offset)."""
    if n <= max_batch:
        return [n]
    sizes = [max_batch] * (n // max_batch)
    if n % max_batch:
        sizes.append(n % max_batch)
    return sizes


class Request:
    """One queued unit of work: ``xs`` is a tuple of per-input row
    blocks (all leading dim ``n``); ``on_done(outputs, now)`` fires on
    the dispatcher thread once the packed batch containing this request
    has been fetched (`outputs` is this request's row slice, or an
    exception on the dispatch error path) and returns True iff this
    call completed the LOGICAL request's future (split chunks share
    one — the error accounting counts completions, not chunks)."""

    __slots__ = ("xs", "n", "on_done", "t_submit")

    def __init__(self, xs, n: int, on_done, t_submit: float):
        self.xs = xs
        self.n = n
        self.on_done = on_done
        self.t_submit = t_submit


class MicroBatcher:
    """Thread-safe coalescing queue between `submit()` callers and the
    single dispatcher thread."""

    def __init__(self, max_batch: int, max_wait_ms: float,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.clock = clock
        self._cv = threading.Condition()
        self._pending: deque[Request] = deque()
        self._rows = 0
        self._closed = False

    # ---- producer side -------------------------------------------------
    def submit(self, req: Request) -> None:
        self.submit_all((req,))

    def submit_all(self, reqs: Sequence[Request]) -> None:
        """Enqueue ``reqs`` atomically: either every request is
        accepted or none is (closed batcher) — the chunks of one split
        oversize request must never half-enqueue around a concurrent
        close(), which would drain orphan chunks whose join future the
        caller never received."""
        for req in reqs:
            if req.n > self.max_batch:
                raise ValueError(
                    f"request of {req.n} rows exceeds max_batch "
                    f"{self.max_batch}; split first (split_sizes)")
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            was_rows = self._rows
            was_empty = not self._pending
            for req in reqs:
                self._pending.append(req)
                self._rows += req.n
            # wake the dispatcher only on a state change it must act
            # on: the queue turning nonempty (a deadline now needs
            # arming) or the batch turning full (dispatch now).
            # Notifying every submit would wake it dozens of times per
            # batch just to re-sleep — measured ~3x engine throughput
            # lost to the GIL ping-pong under a hot submit loop.
            if was_empty or was_rows < self.max_batch <= self._rows:
                self._cv.notify()

    def close(self) -> None:
        """Stop accepting work; `next_batch` drains what is pending and
        then returns None."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    # ---- consumer side -------------------------------------------------
    @property
    def queue_depth(self) -> int:
        """Pending requests (snapshot, for metrics)."""
        return len(self._pending)

    @property
    def pending_rows(self) -> int:
        return self._rows

    def _ready(self, now: float) -> bool:
        if not self._pending:
            return False
        if self._rows >= self.max_batch:
            return True
        return now - self._pending[0].t_submit >= self.max_wait_s

    def _take(self) -> List[Request]:
        """Pop a FIFO prefix of pending requests totalling at most
        ``max_batch`` rows.  Whole requests only (order-preserving, and
        the scatter stays one contiguous slice per request); oversize
        requests were already split at submit."""
        out: List[Request] = []
        rows = 0
        while self._pending and rows + self._pending[0].n <= self.max_batch:
            r = self._pending.popleft()
            rows += r.n
            out.append(r)
        self._rows -= rows
        return out

    def poll(self) -> Optional[List[Request]]:
        """Non-blocking `next_batch`: a coalesced batch if one is due
        (full, past the deadline, or draining after close), else None.
        The deadline-flush unit tests drive this with a fake clock."""
        with self._cv:
            if self._pending and (self._closed or self._ready(self.clock())):
                return self._take()
            return None

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[Request]]:
        """Block until a batch is due, the batcher is closed AND
        drained (returns None — dispatcher exits), or ``timeout``
        expires (returns None; caller re-checks its stop flag)."""
        deadline = None if timeout is None else self.clock() + timeout
        with self._cv:
            while True:
                now = self.clock()
                if self._pending and (self._closed or self._ready(now)):
                    return self._take()
                if self._closed and not self._pending:
                    return None
                # sleep until the oldest request's deadline (or the
                # caller's timeout / a submit notification)
                wait = None
                if self._pending:
                    wait = self._pending[0].t_submit + self.max_wait_s - now
                if deadline is not None:
                    if now >= deadline:
                        return None
                    wait = (deadline - now if wait is None
                            else min(wait, deadline - now))
                self._cv.wait(None if wait is None else max(0.0, wait))
