"""Deterministic fault injection for the elastic training stack.

The recovery paths in :mod:`flexflow_tpu.parallel.elastic` are only
trustworthy if they are exercised by *real* multi-process failures, not
mocks (ISSUE 2; the reference has no failure story at all — SURVEY §5).
This module is the single switchboard: a fault plan is described in the
``FF_FAULT`` environment variable, and the train loop
(``FFModel.train_batch``/``fit``), the checkpoint writer
(``FFModel.save_checkpoint``) and the supervisor (``run_elastic``) each
consult it at well-defined points.  With ``FF_FAULT`` unset every hook
is a cached ``None``-check — no behavior change, no measurable cost.

Grammar (specs joined by ``;``, qualifiers by ``,``)::

    FF_FAULT = spec (";" spec)*
    spec     = kind ":" arg ("," key "=" value)*

    kill_at_step:N        exit hard (os._exit, code 17) after step N completes
    hang_at_step:N        stop making progress after step N (sleep forever —
                          detected by the supervisor's heartbeat monitor)
                          (under fused multi-step dispatch, FFConfig.
                          steps_per_dispatch > 1, both indices round UP to
                          the next window edge — see :func:`on_window`)
    corrupt_ckpt:N        truncate the checkpoint published at step N
    corrupt_ckpt:latest   truncate every checkpoint this process publishes
    spawn_fail_attempt:A  supervisor-side: fail attempt A at spawn time
    slow_rank:R           rank R sleeps ``delay`` (default 0.25 s) per step
    grow_at_step:N        request an in-process mesh GROW after step N
    shrink_at_step:N      request an in-process mesh SHRINK after step N
                          (both consumed by the train loop via
                          :func:`reshard_at_window` — FFModel.reshard();
                          same window-edge rounding as kill/hang; target
                          device count via ``devices=D``, default 2x /
                          half the current mesh)

    serving kinds (consumed by ServingEngine's dispatcher before each
    packed dispatch — :func:`serve_faults`; docs/serving.md "Overload,
    SLOs & degradation"):

    serve_slow_dispatch:N   the first N dispatches each stall ``ms``
                            milliseconds (default 50) through the
                            engine's injectable sleep — deterministic
                            overload without a slow model
    serve_fail_dispatch:N   inject N dispatch failures (RuntimeError on
                            the normal dispatch-error path: the batch's
                            futures fail, serving continues); ``every=K``
                            spaces them every K-th dispatch (default 1 —
                            the first N dispatches fail)
    serve_queue_spike:N     at dispatch index N, push ``rows`` rows
                            (default 4x max_batch) of synthetic load
                            through the real admission path — the
                            bounded-queue/shedding behavior under a
                            burst is the thing being tested

    token-generation kinds (consumed by GenerationEngine's decode loop
    — :func:`generation_faults`; docs/serving.md "Token generation"):

    serve_cancel_at_token:N the FIRST stream to reach N generated
                            tokens is cancelled mid-generation — its
                            KV slot must free and ONLY its own stream
                            fail (fires once)
    serve_slow_decode:N     the first N decode steps each stall ``ms``
                            milliseconds (default 50) through the
                            engine's injectable sleep
    spec_draft_fail:N       the Nth speculative DRAFT dispatch raises
                            (fires once) — the engine must demote to
                            plain decode (serve_health fallback event)
                            with NO stream failing

    model-fleet kinds (consumed by the FleetEngine — :func:`
    fleet_faults`; docs/serving.md "Model fleets"):

    fleet_load_fail:NAME    the registry build of model NAME fails
                            (RuntimeError before compile) — a failed
                            background load/swap must surface a
                            fleet_load_error event and leave every
                            serving tenant untouched.  The arg is the
                            MODEL NAME, not a step index.
    fleet_swap_at_dispatch:N a prepared publish (hot load/swap) is
                            HELD until fleet dispatch index N — pins
                            the dispatch boundary where an atomic
                            swap lands, so swap-under-load tests are
                            deterministic

    router kinds (consumed by the cluster FleetRouter —
    :func:`router_faults`; docs/serving.md "Disaggregated
    prefill/decode"):

    migrate_fail_at:N       the Nth KV page migration handoff raises
                            (fires once) — the source engine must fall
                            back to CO-LOCATED decode (one serve_health
                            fallback event) with NO stream failing
    route_host_down:NAME    host NAME is marked down at the router's
                            first routing decision — its tenants'
                            queued requests drain to surviving hosts
                            (requeue, never re-judged), in-flight
                            streams finish where they run, and no new
                            route/migration targets it.  The arg is the
                            HOST NAME, not a step index.

    qualifiers: rank=R (fire only on rank R), attempt=A or attempt=*
                (default attempt=0 — faults must not re-fire on the
                restarted attempt or recovery could never be observed),
                delay=SECONDS (slow_rank), exit=CODE (kill_at_step),
                devices=D (grow_at_step/shrink_at_step target),
                ms=MILLIS (serve_slow_dispatch, serve_slow_decode),
                every=K (serve_fail_dispatch), rows=R
                (serve_queue_spike)

Examples::

    FF_FAULT="kill_at_step:7,rank=1"
    FF_FAULT="corrupt_ckpt:4;kill_at_step:5,rank=1"
    FF_FAULT="hang_at_step:5,rank=0,attempt=0"

Rank resolution: workers call :func:`set_rank` (the
``resilience.Heartbeat`` helper does it for them); otherwise
``jax.process_index()`` is used when jax is already imported, else rank
0.  A rank-qualified spec never fires when the rank is unknown.  The
attempt comes from ``FF_ELASTIC_ATTEMPT`` (exported by the supervisor).

Deliberately dependency-free (stdlib only) and importable standalone via
``importlib`` file loading, so test workers can inject faults without
paying the ``flexflow_tpu`` package import.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Dict, List, Optional

# exit code for an injected kill — distinguishable from real crashes in
# AttemptResult.returncodes (tests/test_elastic.py pins it)
KILL_EXIT_CODE = 17

KINDS = ("kill_at_step", "hang_at_step", "corrupt_ckpt",
         "spawn_fail_attempt", "slow_rank", "grow_at_step",
         "shrink_at_step", "serve_slow_dispatch", "serve_fail_dispatch",
         "serve_queue_spike", "serve_cancel_at_token",
         "serve_slow_decode", "spec_draft_fail", "fleet_load_fail",
         "fleet_swap_at_dispatch", "migrate_fail_at",
         "route_host_down")

SERVE_KINDS = ("serve_slow_dispatch", "serve_fail_dispatch",
               "serve_queue_spike")

# token-generation kinds (GenerationEngine's decode loop —
# docs/serving.md "Token generation"); disjoint from SERVE_KINDS so a
# plan mixing both drives each engine's own fire points only
GENERATION_KINDS = ("serve_cancel_at_token", "serve_slow_decode",
                    "spec_draft_fail")

# model-fleet kinds (FleetEngine / fleet registry — docs/serving.md
# "Model fleets"); disjoint from both sets above
FLEET_KINDS = ("fleet_load_fail", "fleet_swap_at_dispatch")

# disaggregated-serving router kinds (cluster.FleetRouter —
# docs/serving.md "Disaggregated prefill/decode"); disjoint from every
# set above so a plan mixing engine families drives each one's own
# fire points only
ROUTER_KINDS = ("migrate_fail_at", "route_host_down")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    kind: str
    arg: str
    rank: Optional[int]      # None: any rank
    attempt: Optional[int]   # None: any attempt
    extras: Dict[str, str] = dataclasses.field(default_factory=dict)


def parse_faults(text: Optional[str]) -> List[FaultSpec]:
    """Parse an ``FF_FAULT`` value.  Malformed specs and unknown kinds
    raise ValueError loudly — a typo that silently injects nothing would
    make a fault test vacuously green."""
    specs: List[FaultSpec] = []
    for raw in (text or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        head, _, qual = raw.partition(",")
        kind, sep, arg = head.partition(":")
        kind, arg = kind.strip(), arg.strip()
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in FF_FAULT spec {raw!r} "
                f"(known: {', '.join(KINDS)})")
        if not sep or not arg:
            raise ValueError(f"fault spec {raw!r} is missing ':<arg>'")
        rank: Optional[int] = None
        # default attempt 0: a fault that re-fired on the restarted
        # attempt would defeat every recovery test
        attempt: Optional[int] = 0
        extras: Dict[str, str] = {}
        for kv in qual.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, sep2, val = kv.partition("=")
            if not sep2:
                raise ValueError(
                    f"fault qualifier {kv!r} in {raw!r} is not key=value")
            key, val = key.strip(), val.strip()
            if key == "rank":
                rank = int(val)
            elif key == "attempt":
                attempt = None if val == "*" else int(val)
            elif key in ("delay", "exit", "devices", "ms", "every",
                         "rows"):
                # validate now, fail at parse not at fire — with the
                # type actually used at fire time (exit=9.5 must not
                # blow up inside the train loop)
                (float if key in ("delay", "ms") else int)(val)
                if key in ("devices", "every", "rows") and int(val) < 1:
                    raise ValueError(
                        f"{key} qualifier must be >= 1, got {val!r} "
                        f"in {raw!r}")
                if key == "ms" and float(val) < 0:
                    # a negative stall would turn serve_slow_dispatch
                    # into dispatch FAILURES at fire time (sleep raises)
                    raise ValueError(
                        f"ms qualifier must be >= 0, got {val!r} "
                        f"in {raw!r}")
                extras[key] = val
            else:
                raise ValueError(
                    f"unknown fault qualifier {key!r} in {raw!r}")
        # validate the arg NOW (same policy as delay/exit above): a typo
        # like corrupt_ckpt:latst must fail at parse, not silently
        # inject nothing — or blow up mid-training at fire time
        if kind == "corrupt_ckpt":
            if arg != "latest" and not arg.isdigit():
                raise ValueError(
                    f"corrupt_ckpt arg must be a step number or "
                    f"'latest', got {arg!r} in {raw!r}")
        elif kind in ("fleet_load_fail", "route_host_down"):
            pass  # the arg IS a model/host name — any non-empty string
        elif not (arg.isdigit() or (arg[:1] == "-" and arg[1:].isdigit())):
            raise ValueError(
                f"{kind} arg must be an integer, got {arg!r} in {raw!r}")
        if kind == "spawn_fail_attempt":
            attempt = int(arg)  # the arg IS the attempt
        specs.append(FaultSpec(kind, arg, rank, attempt, extras))
    return specs


# ----------------------------------------------------------------------
# process-local plan (parsed once; reset() for in-process tests)
# ----------------------------------------------------------------------
_UNSET = object()
_plan = _UNSET
_rank: Optional[int] = None


def plan() -> Optional[List[FaultSpec]]:
    """The cached fault plan from ``FF_FAULT``, or None when unset."""
    global _plan
    if _plan is _UNSET:
        text = os.environ.get("FF_FAULT")
        _plan = parse_faults(text) if text else None
    return _plan  # type: ignore[return-value]


def reset() -> None:
    """Drop the cached plan and rank (tests mutate the environment)."""
    global _plan, _rank
    _plan = _UNSET
    _rank = None


def set_rank(rank: int) -> None:
    """Register this process's rank (workers call it at startup; the
    ``resilience.Heartbeat`` helper does it implicitly)."""
    global _rank
    _rank = int(rank)


def current_rank() -> Optional[int]:
    if _rank is not None:
        return _rank
    if "jax" in sys.modules:  # never trigger the heavyweight import
        try:
            return int(sys.modules["jax"].process_index())
        except Exception:
            return None
    return None


def current_attempt() -> int:
    return int(os.environ.get("FF_ELASTIC_ATTEMPT", "0"))


def _matches(spec: FaultSpec) -> bool:
    if spec.attempt is not None and spec.attempt != current_attempt():
        return False
    if spec.rank is not None:
        r = current_rank()
        if r is None or r != spec.rank:
            return False
    return True


def _note(msg: str) -> None:
    # stderr lands in the supervisor's per-rank log tail — forensics for
    # a failed matrix test come for free
    print(f"FF_FAULT: {msg}", file=sys.stderr, flush=True)


# ----------------------------------------------------------------------
# fire points
# ----------------------------------------------------------------------
def on_step(step: int) -> None:
    """Train-loop hook: call after step ``step`` completes.  May sleep
    (slow_rank), stop progressing (hang_at_step) or kill the process
    (kill_at_step).  No-op without an active plan."""
    on_window(step - 1, step)


def on_window(start: int, end: int) -> None:
    """Window-granularity train-loop hook: call after the fused dispatch
    covering steps ``(start, end]`` completes (``FFConfig.
    steps_per_dispatch`` — one host re-entry per K steps).  Fire
    semantics, pinned by tests/test_faults.py so the elastic recovery
    matrix stays honest when windows are enabled:

    * ``kill_at_step:N`` / ``hang_at_step:N`` with ``start < N <= end``
      fire at the WINDOW EDGE — the step index rounds up to ``end``
      (mid-window steps never re-enter Python, so the earliest possible
      fire point is the dispatch boundary);
    * ``slow_rank`` sleeps ``delay`` once per covered step (``end -
      start`` times), preserving the per-step straggler budget.

    ``on_step(step)`` is exactly ``on_window(step - 1, step)``.
    No-op without an active plan."""
    p = plan()
    if not p:
        return
    for spec in p:
        if not _matches(spec):
            continue
        if spec.kind == "slow_rank":
            r = current_rank()
            if r is not None and r == int(spec.arg):
                time.sleep(float(spec.extras.get("delay", "0.25"))
                           * max(1, end - start))
        elif spec.kind == "hang_at_step" and start < int(spec.arg) <= end:
            _note(_edge_note("hang", spec, end))
            while True:  # no progress, no exit: only heartbeat monitoring
                time.sleep(3600)  # (or the attempt timeout) can end this
        elif spec.kind == "kill_at_step" and start < int(spec.arg) <= end:
            code = int(spec.extras.get("exit", str(KILL_EXIT_CODE)))
            _note(_edge_note("kill", spec, end, f"exit {code}"))
            os._exit(code)  # hard crash: no cleanup, no excepthook


def _edge_note(what: str, spec, end: int, extra: str = "") -> str:
    """One message format for every window-edge fire point (kill / hang
    / grow / shrink): what fired, where it rounded from, and the
    rank/attempt scope — kept in one place so the fire-point log the
    fault matrix greps stays consistent across kinds."""
    rounded = (f" (requested step {spec.arg} rounded up to the "
               f"window edge)" if int(spec.arg) != end else "")
    scope = f"rank {current_rank()}, attempt {current_attempt()}"
    if extra:
        scope += f", {extra}"
    return f"injected {what} at step {end}{rounded} ({scope})"


def reshard_at_window(start: int, end: int):
    """Train-loop hook for the elastic-reshard fault kinds: which
    ``grow_at_step:N`` / ``shrink_at_step:N`` specs fall inside the
    just-completed window ``(start, end]``?  Returns a list of
    ``(kind, devices)`` requests in spec order (EVERY matching spec —
    a wide dispatch window may cover two scheduled reshards, and
    dropping the second would silently change the injected plan);
    ``devices`` is the ``devices=D`` qualifier as an int, or None for
    the default scaling (grow doubles, shrink halves the mesh).  Same
    window-edge rounding as kill/hang (a mid-window step index fires
    at the dispatch boundary), and each spec fires at most once: only
    the window CONTAINING its step matches.  The consumer is
    ``FFModel.train_batch``/``train_window``, which performs the
    actual :meth:`FFModel.reshard`; this module stays jax-free."""
    p = plan()
    if not p:
        return []
    out = []
    for spec in p:
        if spec.kind not in ("grow_at_step", "shrink_at_step"):
            continue
        if not _matches(spec):
            continue
        if start < int(spec.arg) <= end:
            devices = spec.extras.get("devices")
            _note(_edge_note(f"{spec.kind.split('_')[0]} reshard", spec,
                             end, f"devices={devices if devices else 'auto'}"))
            out.append((spec.kind, int(devices) if devices else None))
    return out


def generation_faults() -> List[FaultSpec]:
    """The FF_FAULT token-generation specs matching this rank/attempt,
    in plan order (empty without a plan).  The consumer is the
    ``GenerationEngine``, which materializes per-engine firing state at
    ``start()`` and consults it at decode-step boundaries; this module
    stays jax- and engine-free."""
    p = plan()
    if not p:
        return []
    return [s for s in p if s.kind in GENERATION_KINDS and _matches(s)]


def fleet_faults() -> List[FaultSpec]:
    """The FF_FAULT model-fleet specs matching this rank/attempt, in
    plan order (empty without a plan).  Consumers: the fleet registry's
    build path (``fleet_load_fail``) and the FleetEngine's publish
    boundary (``fleet_swap_at_dispatch``); this module stays jax- and
    engine-free."""
    p = plan()
    if not p:
        return []
    return [s for s in p if s.kind in FLEET_KINDS and _matches(s)]


def router_faults() -> List[FaultSpec]:
    """The FF_FAULT disaggregated-router specs matching this
    rank/attempt, in plan order (empty without a plan).  The consumer
    is the cluster ``FleetRouter``, which materializes firing state at
    ``start()`` and consults it at routing/migration boundaries; this
    module stays jax- and engine-free."""
    p = plan()
    if not p:
        return []
    return [s for s in p if s.kind in ROUTER_KINDS and _matches(s)]


def serve_faults() -> List[FaultSpec]:
    """The FF_FAULT serving specs matching this rank/attempt, in plan
    order (empty without a plan — the cached None-check keeps the
    fault-free serving path cost-free).  The consumer is
    ``ServingEngine``, which materializes per-engine firing state at
    ``start()`` and consults it before each packed dispatch; this
    module stays jax- and engine-free."""
    p = plan()
    if not p:
        return []
    return [s for s in p if s.kind in SERVE_KINDS and _matches(s)]


def corrupt_file(path: str) -> None:
    """The corruption primitive: truncate to half size, simulating a
    writer killed mid-write / a disk-full partial flush.  The result is
    not a valid zip, so both ``np.load`` and the checkpoint manifest
    verification reject it."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(1, size // 2))


def maybe_corrupt_checkpoint(path: str, step: int) -> None:
    """Checkpoint-writer hook: call after publishing ``path`` for
    ``step``.  ``corrupt_ckpt:N`` corrupts the step-N file only;
    ``corrupt_ckpt:latest`` corrupts every file this process writes."""
    p = plan()
    if not p:
        return
    for spec in p:
        if spec.kind != "corrupt_ckpt" or not _matches(spec):
            continue
        if spec.arg == "latest" or (spec.arg.isdigit()
                                    and int(spec.arg) == step):
            corrupt_file(path)
            _note(f"injected checkpoint corruption: {path} (step {step})")


def spawn_fail_requested(env: Dict[str, str], attempt: int) -> bool:
    """Supervisor-side hook: should ``attempt`` fail at spawn time?
    Parses the worker environment (not this process's cached plan — the
    supervisor's own FF_FAULT may differ from what it exports)."""
    text = env.get("FF_FAULT")
    if not text:
        return False
    return any(s.kind == "spawn_fail_attempt" and s.attempt == attempt
               for s in parse_faults(text))
