"""Worker-side resilience helpers for elastic training.

Three small pieces, shared by the supervisor
(:mod:`flexflow_tpu.parallel.elastic`), the checkpoint layer
(:meth:`FFModel.save_checkpoint` / :meth:`load_checkpoint`) and elastic
worker scripts (``tests/_elastic_worker.py``, ``flexflow-tpu elastic``):

* **Heartbeats** — each rank stamps ``<dir>/rank<r>.hb`` with its step
  number once per step (atomic tmp+rename, so the supervisor never reads
  a torn write).  The supervisor only compares *contents across reads
  with its own clock* — the monotonic/wall times in the file are
  per-process and recorded for human forensics, never compared across
  machines.
* **Checkpoint manifest + verification** — ``build_manifest`` embeds a
  per-array CRC32 table (plus step and format version) under the
  ``meta:manifest`` key of the checkpoint ``.npz``; ``verify_checkpoint``
  re-reads a file end to end and checks every CRC, turning "is this
  checkpoint trustworthy?" into a cheap local question the restart path
  can ask *before* resuming from it.
* **Atomic publish** — ``_atomic_savez`` is the single tmp+rename writer
  used by both ``save_checkpoint`` and keras ``save_weights`` (they had
  drifted into two copies).

Import-light on purpose: numpy + stdlib only, never jax — the supervisor
process must stay cheap and the helpers must work before/without a jax
runtime.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Dict, Optional

import numpy as np

from . import faults

#: npz key holding the JSON manifest (kept in ``meta:`` space alongside
#: ``meta:step`` so param/opt key enumeration is unaffected)
MANIFEST_KEY = "meta:manifest"
#: v1: per-array CRC32 table + step.  v2 adds the TOPOLOGY the
#: checkpoint was saved under — ``mesh_shape``/``num_devices``/
#: ``process_count``/``strategy_digest`` — so a resume can detect a
#: mesh change and reshard instead of assuming the world it died on
#: (docs/elastic.md "Resharding").  v1 and manifest-less archives keep
#: verifying unchanged.
MANIFEST_VERSION = 2


class CorruptNpzError(RuntimeError):
    """A ``.npz`` archive (checkpoint or dataset) that cannot be read —
    truncated, bit-rotted, or failing its manifest CRCs."""


class CorruptCheckpointError(CorruptNpzError):
    """A checkpoint that failed verification; the raiser names the path
    and the fallback (``latest_valid_checkpoint`` / ``elastic_resume``)."""


# ----------------------------------------------------------------------
# atomic publish (shared by model.save_checkpoint and keras save_weights)
# ----------------------------------------------------------------------
def _atomic_savez(final: str, arrays: Dict[str, np.ndarray]) -> str:
    """Write ``arrays`` to ``final`` (.npz) via tmp + rename: a crash or
    kill mid-write never leaves a truncated file at the published name.
    The tmp keeps the ``.npz`` suffix because ``np.savez`` appends it to
    suffix-less paths."""
    assert final.endswith(".npz"), final
    tmp = final[:-len(".npz")] + ".tmp.npz"
    np.savez(tmp, **arrays)
    os.replace(tmp, final)
    return final


# ----------------------------------------------------------------------
# checkpoint manifest
# ----------------------------------------------------------------------
def _crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def build_manifest(arrays: Dict[str, np.ndarray], step: int,
                   mesh_shape: Optional[Dict[str, int]] = None,
                   num_devices: Optional[int] = None,
                   process_count: Optional[int] = None,
                   strategy_digest: Optional[str] = None) -> str:
    """JSON manifest for a checkpoint's arrays: per-array CRC32 + shape +
    dtype, the step, and a format version — plus (v2) the topology the
    checkpoint was saved under, when the writer knows it: mesh axis
    sizes, device and process counts, and a digest of the resolved
    parallel strategy (``strategy.proto.strategy_digest``).  The
    topology fields are advisory (resume uses them to DETECT a mesh
    change, never to place arrays — checkpoints always hold full global
    arrays), so ``None`` simply omits them."""
    man: Dict = {
        "format_version": MANIFEST_VERSION,
        "step": int(step),
        "arrays": {
            k: {"crc32": _crc(np.asarray(v)),
                "shape": list(np.asarray(v).shape),
                "dtype": str(np.asarray(v).dtype)}
            for k, v in arrays.items()},
    }
    if mesh_shape is not None:
        man["mesh_shape"] = {str(a): int(s) for a, s in mesh_shape.items()}
    if num_devices is not None:
        man["num_devices"] = int(num_devices)
    if process_count is not None:
        man["process_count"] = int(process_count)
    if strategy_digest is not None:
        man["strategy_digest"] = str(strategy_digest)
    return json.dumps(man, sort_keys=True)


def manifest_meta(data: Dict[str, np.ndarray]) -> Optional[Dict]:
    """The parsed manifest of already-loaded checkpoint ``data`` with
    the v2 topology fields normalized: keys ``format_version``/``step``
    always present, ``mesh_shape``/``num_devices``/``process_count``/
    ``strategy_digest`` present-or-None (v1 and partial manifests read
    the same way).  None for manifest-less archives; an unreadable
    manifest raises like :func:`verify_manifest` (the caller has
    already decided to trust this file, so silence would hide rot)."""
    if MANIFEST_KEY not in data:
        return None
    try:
        man = json.loads(str(np.asarray(data[MANIFEST_KEY])))
        meta = {"format_version": int(man["format_version"]),
                "step": int(man["step"])}
        mesh = man.get("mesh_shape")
        meta["mesh_shape"] = ({str(a): int(s) for a, s in mesh.items()}
                              if isinstance(mesh, dict) else None)
        for k in ("num_devices", "process_count"):
            v = man.get(k)
            meta[k] = int(v) if v is not None else None
        d = man.get("strategy_digest")
        meta["strategy_digest"] = str(d) if d is not None else None
    except (ValueError, KeyError, TypeError, AttributeError) as e:
        raise CorruptCheckpointError(
            f"checkpoint manifest is unreadable "
            f"({type(e).__name__}: {e})") from e
    return meta


def verify_manifest(data: Dict[str, np.ndarray], path: str = "<npz>") -> None:
    """Check loaded checkpoint ``data`` against its embedded manifest.
    Manifest-less archives (pre-manifest checkpoints) pass — readability
    was already proven by loading them.  Raises
    :class:`CorruptCheckpointError` on any mismatch."""
    if MANIFEST_KEY not in data:
        return
    try:
        man = json.loads(str(np.asarray(data[MANIFEST_KEY])))
        version = int(man["format_version"])
        entries = man["arrays"]
    except (ValueError, KeyError, TypeError) as e:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} has an unreadable manifest "
            f"({type(e).__name__}: {e})") from e
    if version > MANIFEST_VERSION:
        raise CorruptCheckpointError(
            f"checkpoint {path!r} has manifest format_version {version}; "
            f"this build understands <= {MANIFEST_VERSION}")
    payload = {k: v for k, v in data.items() if k != MANIFEST_KEY}
    if set(entries) != set(payload):
        raise CorruptCheckpointError(
            f"checkpoint {path!r} manifest names "
            f"{len(entries)} arrays but the archive holds {len(payload)}")
    for k, v in payload.items():
        if _crc(v) != int(entries[k]["crc32"]):
            raise CorruptCheckpointError(
                f"checkpoint {path!r} failed CRC verification for "
                f"array {k!r} — the file is corrupt; an elastic resume "
                f"should fall back to the next-newest valid checkpoint "
                f"(latest_valid_checkpoint / elastic_resume)")


def read_npz_verified(path: str, what: str = "checkpoint"
                      ) -> Dict[str, np.ndarray]:
    """Read a whole ``.npz`` into host arrays, translating the opaque
    low-level failures of a truncated/corrupt archive
    (``zipfile.BadZipFile``, bare ``ValueError``/``OSError``) into a
    :class:`CorruptCheckpointError` that names the path, then checking
    the embedded manifest when present."""
    import zipfile
    try:
        with np.load(path, allow_pickle=False) as f:
            data = {k: np.asarray(f[k]) for k in f.files}
    except (zipfile.BadZipFile, zlib.error, ValueError, OSError, EOFError,
            KeyError) as e:
        raise CorruptCheckpointError(
            f"{what} {path!r} is corrupt or truncated "
            f"({type(e).__name__}: {e}); if this is an elastic run, "
            f"resume from the next-newest valid file via "
            f"latest_valid_checkpoint() / elastic_resume()") from e
    verify_manifest(data, path)
    return data


def iter_valid_checkpoints(directory: str, prefix: str = "elastic"):
    """Yield ``(step, path, data)`` for every VERIFIED checkpoint in
    ``directory`` newest-first (one full read + CRC pass each), emitting
    a structured ``checkpoint_skipped`` event — path, step, why — for
    every corrupt/truncated candidate instead of silence.  THE shared
    scan under both resume paths: the supervisor-side
    ``parallel.elastic.latest_valid_checkpoint`` and the worker-side
    :func:`elastic_resume` must never diverge on what they skip or how
    they report it."""
    from .parallel.elastic import _step_checkpoints
    for step, path in _step_checkpoints(directory, prefix):
        try:
            data = read_npz_verified(path, what="checkpoint")
        except CorruptNpzError as e:
            from .fflogger import get_logger
            get_logger("elastic").event(
                "checkpoint_skipped", path=path, step=step,
                reason=f"{type(e).__name__}: {e}")
            continue
        yield step, path, data


def verify_checkpoint(path: str) -> bool:
    """True iff ``path`` is a readable checkpoint whose manifest (when
    present) verifies.  Reads the whole file — that is the point: a
    verdict cheaper than reading cannot rule out truncation."""
    try:
        read_npz_verified(path)
        return True
    except CorruptNpzError:
        return False


# ----------------------------------------------------------------------
# heartbeats
# ----------------------------------------------------------------------
class Heartbeat:
    """Per-rank progress stamp.  Workers call :meth:`beat` once per
    completed step; the supervisor's hang monitor reads the directory and
    kills the attempt when *no* rank's step advances for
    ``hang_timeout_s``.  Disabled (every call a no-op) when no directory
    is configured, so worker code can call it unconditionally.

    File protocol: ``<dir>/rank<r>.hb`` containing one line
    ``"<step> <monotonic> <wall>"``, published atomically.
    """

    def __init__(self, directory: Optional[str] = None,
                 rank: Optional[int] = None):
        self.dir = directory if directory is not None \
            else os.environ.get("FF_HEARTBEAT_DIR")
        self.rank = int(rank) if rank is not None else 0
        if rank is not None:
            faults.set_rank(rank)  # one registration point for workers
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return bool(self.dir)

    def beat(self, step: int) -> None:
        if not self.dir:
            return
        final = os.path.join(self.dir, f"rank{self.rank}.hb")
        tmp = final + ".tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(f"{int(step)} {time.monotonic():.3f} "
                         f"{time.time():.3f}\n")
            os.replace(tmp, final)
        except OSError:
            pass  # a failed beat must never kill training


def read_heartbeats(directory: str) -> Dict[int, int]:
    """Supervisor side: ``{rank: last_step}`` from a heartbeat dir.
    Unparseable/partial files are skipped (the next beat replaces them)."""
    out: Dict[int, int] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for n in names:
        if not (n.startswith("rank") and n.endswith(".hb")):
            continue
        try:
            rank = int(n[len("rank"):-len(".hb")])
            with open(os.path.join(directory, n)) as fh:
                out[rank] = int(fh.read().split()[0])
        except (OSError, ValueError, IndexError):
            continue
    return out


# ----------------------------------------------------------------------
# the standard worker resume pattern
# ----------------------------------------------------------------------
def elastic_resume(model, workdir: str, prefix: str = "elastic"
                   ) -> Optional[str]:
    """Load the newest *valid* checkpoint from ``workdir`` into
    ``model`` (skipping corrupt/truncated files — a bit-rotted newest
    checkpoint costs one save interval, not the whole job).  Returns the
    path resumed from, or None for a fresh start.

    Probes candidates newest-first (:func:`iter_valid_checkpoints` —
    one read + CRC pass each, structured ``checkpoint_skipped`` events
    for corrupt files) and restores straight from the winning read — a
    multi-GB checkpoint on shared storage is not read twice per rank at
    the exact moment the job is recovering (vs
    ``latest_valid_checkpoint`` + ``load_checkpoint``, which would
    verify then re-read).

    Topology changes are first-class: when the winning checkpoint's
    manifest records a different mesh than the model is compiled for
    (the mesh shrank or grew between the save and this resume),
    ``FFModel._reshard_if_mesh_changed`` re-resolves strategies for the
    CURRENT mesh before the restore — reshard-on-resume
    (docs/elastic.md "Resharding")."""
    model.wait_for_checkpoint()  # never read under a pending writer
    for _, path, data in iter_valid_checkpoints(workdir, prefix):
        # graph/optimizer mismatch must fail with the model untouched —
        # the reshard below zero-fills state ahead of the restore
        model._validate_restore(data)
        model._reshard_if_mesh_changed(data, path)
        model._restore_from_host(data)
        return path
    return None
