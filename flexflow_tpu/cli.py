"""``flexflow-tpu`` console entry — the reference's ``flexflow_python``
runner (python/Makefile, flexflow_top.py:164-220): parses the FlexFlow flag
set into an FFConfig, installs it as the process default, and executes the
user script.

    flexflow-tpu my_model.py -b 64 -e 10 --lr 0.01 -ll:tpu 8 --budget 500

Where the reference launches the script as a Legion top-level task, here the
script simply runs under CPython with ``FFConfig.parse_args``'s result made
available via :func:`flexflow_tpu.get_default_config` (scripts may also call
``FFConfig.parse_args()`` themselves, same flags)."""

from __future__ import annotations

import os
import runpy
import sys

from .config import FFConfig


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # built-in subcommands (no user script involved)
    if argv and argv[0] == "search-bench":
        # search-throughput microbenchmark: delta vs full re-simulation
        # (JSON to stdout; see docs/strategy_search.md)
        from .search.bench import main as bench_main
        bench_main(argv[1:])
        return
    if argv and argv[0] == "train-bench":
        # dispatch-amortization microbenchmark: fit() steps/s across
        # steps_per_dispatch values (JSON to stdout; docs/performance.md)
        from .train_bench import main as train_bench_main
        train_bench_main(argv[1:])
        return
    if argv and argv[0] == "serve-bench":
        # serving-engine microbenchmark: bucketed AOT + micro-batching
        # vs naive per-request predict (JSON to stdout; docs/serving.md)
        from .serving.bench import main as serve_bench_main
        serve_bench_main(argv[1:])
        return
    if argv and argv[0] == "precision-bench":
        # precision axis + int8 serving evidence artifact
        # (docs/performance.md "Precision policy")
        from .precision_bench import main as precision_bench_main
        precision_bench_main(argv[1:])
        return
    if argv and argv[0] == "calibrate":
        # harvest measured op/dispatch timings into a CalibrationTable,
        # or --check existing artifacts (docs/strategy_search.md)
        from .search.calibration import calibrate_main
        raise SystemExit(calibrate_main(argv[1:]))
    if argv and argv[0] == "calibrate-bench":
        # sim-vs-measured MAPE sweep, analytic vs calibrated estimators
        # (docs/performance.md "Calibration")
        from .search.calibration import calibrate_bench_main
        raise SystemExit(calibrate_bench_main(argv[1:]))
    if argv and argv[0] == "elastic":
        # supervised multi-process training with restart-from-checkpoint
        # (docs/elastic.md)
        raise SystemExit(elastic_main(argv[1:]))
    if argv and argv[0] == "lint":
        # static strategy/graph verifier (docs/verifier.md)
        raise SystemExit(lint_main(argv[1:]))
    if argv and argv[0] == "explain":
        # device-free sharding/communication/memory report for a
        # strategy on a mesh you may not own yet (docs/verifier.md)
        raise SystemExit(explain_main(argv[1:]))
    if argv and argv[0] == "trace":
        # export/inspect recorded request-span traces
        # (docs/observability.md)
        from .obs.trace import trace_main
        raise SystemExit(trace_main(argv[1:]))
    if argv and argv[0] == "flight":
        # flight-recorder post-mortem dumps (docs/observability.md)
        from .obs.flight import flight_main
        raise SystemExit(flight_main(argv[1:]))
    script = None
    for a in argv:
        if a.endswith(".py"):
            script = a
            break
    if script is None:
        print("usage: flexflow-tpu <script.py> [FlexFlow flags]\n"
              "       flexflow-tpu elastic [supervisor flags] -- "
              "<script.py> [script args]\n"
              "       flexflow-tpu search-bench [flags]\n"
              "       flexflow-tpu train-bench [flags]\n"
              "       flexflow-tpu serve-bench [--overload|--generate"
              " [--prefix|--speculate]|--fleet|--disagg] [flags]\n"
              "       flexflow-tpu precision-bench [--out f.json]\n"
              "       flexflow-tpu calibrate [--out table.json | "
              "--check FILE...]\n"
              "       flexflow-tpu calibrate-bench --table table.json "
              "[--out report.json]\n"
              "       flexflow-tpu lint --model NAME [--strategy s.pb] "
              "[--devices N] [--json]\n"
              "       flexflow-tpu lint --fleet fleet.json "
              "[--hbm-gb G] [--json]\n"
              "       flexflow-tpu explain --model NAME [--strategy "
              "s.pb] [--mesh n=4,c=2] [--json]\n"
              "       flexflow-tpu explain --fleet fleet.json [--json]\n"
              "       flexflow-tpu trace export RAW.json [--out f.json]\n"
              "       flexflow-tpu flight dump|show [--dir D]\n"
              "flags (reference model.cc:1221-1289): -e -b --lr --wd -d "
              "--budget --alpha --search-mode --best-known "
              "--reshard-budget -s/-import -ll:tpu "
              "-ll:cpu --nodes --profiling --seed --remat "
              "--steps-per-dispatch --pad-tail --calibration "
              "--cost-estimator "
              "--serve-max-batch --serve-max-wait-ms --serve-buckets "
              "--serve-max-queue-rows --serve-admission "
              "--serve-starvation-ms --trace-sample-rate --metrics-port",
              file=sys.stderr)
        raise SystemExit(2)
    flags = [a for a in argv if a != script]
    cfg = FFConfig.parse_args(flags)
    import flexflow_tpu
    flexflow_tpu.set_default_config(cfg)
    # observability plane (docs/observability.md): a fatal uncaught
    # exception in the user script dumps the flight ring before the
    # traceback prints; --metrics-port exposes the process registry
    from .obs.flight import install_excepthook
    install_excepthook()
    if cfg.metrics_port > 0:
        from .obs.registry import start_metrics_server
        server = start_metrics_server(cfg.metrics_port,
                                      host=cfg.metrics_host)
        print(f"[obs] metrics on {cfg.metrics_host}:"
              f"{server.server_port}/metrics", file=sys.stderr)
    # bring up the multi-host runtime when this is one process of a slice
    # (single-process runs are a no-op) — the reference's GASNet bring-up
    # happens likewise before the top-level task runs.  --nodes > 1 makes
    # the multi-host requirement explicit: failing to form the world is an
    # error, not N disconnected replicas.
    from flexflow_tpu.parallel import initialize_distributed
    initialize_distributed(
        num_processes=cfg.num_nodes if cfg.num_nodes > 1 else None)
    # the script sees the remaining argv like any __main__
    sys.argv = [script] + flags
    runpy.run_path(script, run_name="__main__")


def _lint_builders():
    """Builtin-model registry for ``lint``: name -> zero-config builder
    returning an FFModel.  Lazy imports keep ``lint --help`` fast."""
    from .models import (build_alexnet, build_candle_uno, build_dlrm,
                         build_inception_v3, build_nmt, build_resnet50,
                         build_transformer)
    return {
        "transformer": lambda cfg: build_transformer(cfg)[0],
        # 8 tables make the default interact width (8*64+64) match
        # mlp_top[0]=576 (the reference run-script shape)
        "dlrm": lambda cfg: build_dlrm(
            cfg, embedding_size=(1000000,) * 8)[0],
        "alexnet": lambda cfg: build_alexnet(cfg)[0],
        "resnet": lambda cfg: build_resnet50(cfg)[0],
        "inception": lambda cfg: build_inception_v3(cfg)[0],
        "nmt": lambda cfg: build_nmt(cfg)[0],
        "candle_uno": lambda cfg: build_candle_uno(cfg)[0],
    }


def lint_main(argv) -> int:
    """``flexflow-tpu lint --model transformer --strategy s.pb``: run the
    static verifier (flexflow_tpu.analysis) over a builtin model graph +
    a strategy file and print structured FFxxx diagnostics.  Exit codes:
    0 clean (INFO/WARN only), 1 any ERROR diagnostic, 2 usage/load
    failure.  Entirely device-free: a 1024-chip strategy lints on a
    laptop (no mesh is built, nothing is traced)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="flexflow-tpu lint",
        description="statically verify a strategy against a builtin "
                    "model graph (docs/verifier.md), or a whole model "
                    "fleet's co-residency (--fleet, docs/serving.md "
                    "'Model fleets')")
    parser.add_argument("--model",
                        help=f"builtin graph: "
                             f"{', '.join(sorted(_lint_builders()))}")
    parser.add_argument("--fleet", default="",
                        help="fleet registry JSON: run the static "
                             "co-residency gate over every tenant "
                             "(summed FF108 + KV bytes vs the HBM "
                             "budget — FF130 on overflow) instead of "
                             "a single-model lint")
    parser.add_argument("--strategy", default="",
                        help="strategy .pb (reference wire format); "
                             "omit to lint the graph alone")
    parser.add_argument("--devices", type=int, default=0,
                        help="machine size device ids must fit "
                             "(default: inferred mesh product)")
    parser.add_argument("--mesh", default="",
                        help="mesh factorization, e.g. n=4,c=2 "
                             "(default: inferred from the strategy)")
    parser.add_argument("-b", "--batch-size", type=int, default=64)
    parser.add_argument("--hbm-gb", type=float, default=0.0,
                        help="per-chip HBM budget override in GB "
                             "(default: attached/assumed device spec)")
    parser.add_argument("--calibration", default="",
                        help="CalibrationTable JSON (flexflow-tpu "
                             "calibrate): applies its measured "
                             "DeviceSpec overrides and xla_temp_factor "
                             "to the FF108 HBM pass, so lint judges "
                             "the same calibrated budget the search "
                             "does (docs/strategy_search.md)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--concurrency", action="store_true",
                        help="run the whole-program lock-discipline "
                             "pass (FF150-FF154, docs/concurrency.md) "
                             "over flexflow_tpu/ instead of a "
                             "model/strategy lint")
    parser.add_argument("--no-resharding", action="store_true",
                        help="skip the FF109 hotspot report")
    parser.add_argument("--serve-slots", type=int, default=0,
                        help="size a token-generation deployment: add "
                             "the KV cache for N concurrent decode "
                             "slots to the FF108/FF121 memory gates "
                             "(docs/serving.md 'Token generation')")
    parser.add_argument("--serve-seq", type=int, default=0,
                        help="generation cache length per slot "
                             "(default: the model's sequence length)")
    parser.add_argument("--serve-kv-page", type=int, default=0,
                        help="KV page size of the deployment being "
                             "sized (default: the engine default) — "
                             "pass the same value the engine runs "
                             "with, or lint charges a different pool")
    parser.add_argument("--serve-kv-pages", type=int, default=0,
                        help="KV pool pages (0 = auto, the dense "
                             "worst case slots x ceil(seq/page))")
    args = parser.parse_args(argv)

    if args.concurrency:
        from .analysis.concurrency import concurrency_main
        return concurrency_main(as_json=args.json)
    if args.fleet:
        return _lint_fleet(args)
    builders = _lint_builders()
    if args.model is None:
        print("lint: --model is required (or --fleet / --concurrency "
              "for the whole-tree gates)", file=sys.stderr)
        return 2
    if args.model not in builders:
        print(f"lint: unknown model {args.model!r} (have "
              f"{', '.join(sorted(builders))})", file=sys.stderr)
        return 2
    from .config import FFConfig
    cfg = FFConfig(batch_size=args.batch_size)
    model = builders[args.model](cfg)

    strategies = None
    if args.strategy:
        from .strategy.proto import load_strategy_file
        try:
            strategies = load_strategy_file(args.strategy)
        except (OSError, ValueError) as e:
            print(f"lint: cannot load {args.strategy}: {e}",
                  file=sys.stderr)
            return 2

    mesh_shape = None
    if args.mesh:
        try:
            mesh_shape = {k: int(v) for k, v in
                          (kv.split("=") for kv in args.mesh.split(","))}
            from .parallel.mesh import AbstractMesh
            AbstractMesh(mesh_shape)  # axis-name/size validation
        except ValueError as e:
            print(f"lint: bad --mesh {args.mesh!r} (want n=4,c=2): {e}",
                  file=sys.stderr)
            return 2

    spec = None
    temp_factor = None
    if args.calibration:
        from .search.calibration import CalibrationTable, calibrated_spec
        try:
            table = CalibrationTable.load(args.calibration)
        except (OSError, ValueError) as e:
            print(f"lint: cannot load {args.calibration}: {e}",
                  file=sys.stderr)
            return 2
        spec = calibrated_spec(table)
        temp_factor = table.xla_temp_factor
    if args.hbm_gb > 0:
        import dataclasses

        from .search.cost_model import spec_for_device
        spec = dataclasses.replace(spec or spec_for_device(),
                                   hbm_capacity=args.hbm_gb * 1e9)

    kv_bytes = 0.0
    if args.serve_kv_page < 0 or args.serve_kv_pages < 0:
        print("lint: --serve-kv-page/--serve-kv-pages must be >= 0 "
              "(0 = default/auto)", file=sys.stderr)
        return 2
    if args.serve_slots > 0:
        # the generation engine's preallocated KV cache — the SAME
        # scalar the runtime reports (analysis.kv_memory), so the FF108
        # gate and the engine cannot disagree about deployment fit
        from .analysis.kv_memory import (default_serve_seq, dtype_bytes,
                                         kv_cache_bytes)
        seq = args.serve_seq or default_serve_seq(model.input_tensors)
        if not seq or seq <= 0:
            print("lint: --serve-slots needs --serve-seq (the model "
                  "has no sequence-shaped input to default from)",
                  file=sys.stderr)
            return 2
        shape_for_kv = mesh_shape
        if shape_for_kv is None:
            from .analysis.strategy_passes import infer_mesh_shape
            shape_for_kv, _ = infer_mesh_shape(
                strategies or {}, model.layers, args.devices or 10 ** 9)
        kv_bytes = kv_cache_bytes(
            model.layers, shape_for_kv, args.serve_slots, seq,
            kv_dtype_bytes=dtype_bytes(cfg.compute_dtype),
            page_size=args.serve_kv_page,
            num_pages=args.serve_kv_pages)

    from .analysis import verify
    report = verify(
        model.layers, strategies, mesh_shape=mesh_shape,
        num_devices=args.devices or None,
        input_tensors=model.input_tensors,
        final_tensors=model.layers[-1].outputs if model.layers else (),
        parameters=model.parameters, spec=spec,
        xla_temp_factor=temp_factor,
        check_resharding=not args.no_resharding,
        extra_state_bytes=kv_bytes)
    print(report.render_json() if args.json else report.render_text())
    return 1 if report.errors else 0


def _load_fleet_registry(path: str, what: str):
    """Load + schema-validate a fleet registry JSON for lint/explain
    (returns the registry or prints the problems and returns None)."""
    import json as _json

    from .serving.fleet import ModelRegistry, validate_fleet_json
    try:
        with open(path) as f:
            obj = _json.load(f)
    except (OSError, ValueError) as e:
        print(f"{what}: cannot load {path}: {e}", file=sys.stderr)
        return None
    probs = validate_fleet_json(obj)
    if probs:
        for p in probs:
            print(f"{what}: {path}: {p}", file=sys.stderr)
        return None
    try:
        return ModelRegistry.from_json(obj)
    except ValueError as e:
        print(f"{what}: {path}: {e}", file=sys.stderr)
        return None


def _lint_fleet(args) -> int:
    """``flexflow-tpu lint --fleet fleet.json``: the device-free
    co-residency gate — does the whole fleet FIT on the HBM?  Sums the
    FF108-accounted per-device peak (+ KV caches for generation
    tenants) across every tenant; exit 1 on FF130 (over budget), with
    an FF131 INFO breakdown row per tenant either way."""
    registry = _load_fleet_registry(args.fleet, "lint")
    if registry is None:
        return 2
    spec = None
    temp_factor = None
    if args.calibration:
        from .search.calibration import CalibrationTable, calibrated_spec
        try:
            table = CalibrationTable.load(args.calibration)
        except (OSError, ValueError) as e:
            print(f"lint: cannot load {args.calibration}: {e}",
                  file=sys.stderr)
            return 2
        spec = calibrated_spec(table)
        temp_factor = table.xla_temp_factor
    if args.hbm_gb > 0:
        hbm_gb = args.hbm_gb
    else:
        hbm_gb = registry.hbm_gb
    from .serving.fleet import fleet_gate_report
    report, _rows = fleet_gate_report(
        registry, hbm_gb=hbm_gb, device_spec=spec,
        xla_temp_factor=temp_factor)
    print(report.render_json() if args.json else report.render_text())
    return 1 if report.errors else 0


def explain_main(argv) -> int:
    """``flexflow-tpu explain --model M --strategy s.pb --mesh n=16,c=4``:
    the static what-will-the-runtime-do report (docs/verifier.md
    "explain") — propagated shardings, predicted FF120 replicate
    fallbacks, the per-edge communication plan (reshard/allgather/
    allreduce volumes + ``comm_plan_digest``), and the liveness HBM
    timeline with its peak-owning ops.  Entirely device-free: a
    64-device mesh spec is explained from a CPU-only machine without
    allocating a single jax device.  Exit codes: 0 report produced,
    2 usage/load failure (unlike lint, explain REPORTS — it does not
    gate; run lint for the pass/fail judgement)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="flexflow-tpu explain",
        description="device-free sharding / communication / memory "
                    "report for a strategy (docs/verifier.md)")
    parser.add_argument("--model",
                        help=f"builtin graph: "
                             f"{', '.join(sorted(_lint_builders()))}")
    parser.add_argument("--fleet", default="",
                        help="fleet registry JSON: report every "
                             "tenant's per-device residency breakdown "
                             "(params + KV + FF108 peak) and the fleet "
                             "total instead of a single-model report")
    parser.add_argument("--strategy", default="",
                        help="strategy .pb; omit for the default "
                             "data-parallel plan")
    parser.add_argument("--mesh", default="",
                        help="mesh factorization, e.g. n=16,c=4 "
                             "(default: inferred from the strategy)")
    parser.add_argument("--devices", type=int, default=0,
                        help="machine size (default: mesh product)")
    parser.add_argument("-b", "--batch-size", type=int, default=64)
    parser.add_argument("--hbm-gb", type=float, default=0.0,
                        help="per-chip HBM budget override in GB")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--out", default="",
                        help="also write the JSON report here")
    parser.add_argument("--serve-slots", type=int, default=0,
                        help="size a token-generation deployment: "
                             "report the KV cache for N decode slots "
                             "inside the memory timeline")
    parser.add_argument("--serve-seq", type=int, default=0,
                        help="generation cache length per slot "
                             "(default: the model's sequence length)")
    parser.add_argument("--serve-kv-page", type=int, default=0,
                        help="KV page size of the deployment being "
                             "explained (default: the engine default)")
    parser.add_argument("--serve-kv-pages", type=int, default=0,
                        help="KV pool pages (0 = auto, the dense "
                             "worst case)")
    args = parser.parse_args(argv)

    if args.fleet:
        return _explain_fleet(args)
    builders = _lint_builders()
    if args.model is None:
        print("explain: --model is required (or --fleet for the "
              "residency breakdown)", file=sys.stderr)
        return 2
    if args.model not in builders:
        print(f"explain: unknown model {args.model!r} (have "
              f"{', '.join(sorted(builders))})", file=sys.stderr)
        return 2
    from .config import FFConfig
    cfg = FFConfig(batch_size=args.batch_size)
    model = builders[args.model](cfg)

    strategies = None
    if args.strategy:
        from .strategy.proto import load_strategy_file
        try:
            strategies = load_strategy_file(args.strategy)
        except (OSError, ValueError) as e:
            print(f"explain: cannot load {args.strategy}: {e}",
                  file=sys.stderr)
            return 2

    mesh_shape = None
    if args.mesh:
        try:
            mesh_shape = {k: int(v) for k, v in
                          (kv.split("=") for kv in args.mesh.split(","))}
            from .parallel.mesh import AbstractMesh
            AbstractMesh(mesh_shape)  # axis-name/size validation
        except ValueError as e:
            print(f"explain: bad --mesh {args.mesh!r} (want n=4,c=2): "
                  f"{e}", file=sys.stderr)
            return 2

    spec = None
    if args.hbm_gb > 0:
        import dataclasses

        from .search.cost_model import spec_for_device
        spec = dataclasses.replace(spec_for_device(),
                                   hbm_capacity=args.hbm_gb * 1e9)

    if args.serve_kv_page < 0 or args.serve_kv_pages < 0:
        print("explain: --serve-kv-page/--serve-kv-pages must be >= 0 "
              "(0 = default/auto)", file=sys.stderr)
        return 2
    serve_seq = args.serve_seq
    if args.serve_slots > 0 and serve_seq <= 0:
        from .analysis.kv_memory import default_serve_seq
        serve_seq = default_serve_seq(model.input_tensors) or 0
        if serve_seq <= 0:
            print("explain: --serve-slots needs --serve-seq (the model "
                  "has no sequence-shaped input to default from)",
                  file=sys.stderr)
            return 2

    from .analysis import explain_report, render_explain_text
    rep = explain_report(
        args.model, model.layers, strategies, mesh_shape=mesh_shape,
        num_devices=args.devices or None, spec=spec,
        serve_slots=args.serve_slots, serve_seq=serve_seq,
        serve_kv_page=args.serve_kv_page,
        serve_kv_pages=args.serve_kv_pages)
    if args.json:
        import json as _json
        text = _json.dumps(rep, indent=2)
    else:
        text = render_explain_text(rep)
    print(text)
    if args.out:
        import json as _json
        with open(args.out, "w") as f:
            f.write(_json.dumps(rep, indent=2) + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)
    return 0


def _explain_fleet(args) -> int:
    """``flexflow-tpu explain --fleet fleet.json``: per-tenant
    residency breakdown (params / KV / FF108 peak bytes, each tenant's
    mesh) + the fleet total vs the HBM budget — the report half of the
    co-residency gate (run ``lint --fleet`` for the pass/fail
    judgement)."""
    registry = _load_fleet_registry(args.fleet, "explain")
    if registry is None:
        return 2
    from .serving.fleet import fleet_gate_report
    from .serving.fleet.gate import resolve_budget
    hbm_gb = args.hbm_gb or registry.hbm_gb
    report, rows = fleet_gate_report(registry, hbm_gb=hbm_gb)
    # the verdict IS the gate's: FF130 present <=> over budget — the
    # report half must never re-derive (and potentially contradict)
    # what lint --fleet gates on
    budget = resolve_budget(hbm_gb)
    total = sum(r["ff108_bytes"] for r in rows)
    rep = {
        "fleet": args.fleet,
        "hbm_budget_gb": round(budget / 1e9, 3),
        "total_gb": round(total / 1e9, 3),
        "fits": not report.errors,
        "tenants": rows,
    }
    if args.json:
        import json as _json
        text = _json.dumps(rep, indent=2)
    else:
        lines = [f"fleet {args.fleet}: {len(rows)} tenant(s), "
                 f"{rep['total_gb']} GB / {rep['hbm_budget_gb']} GB "
                 f"budget — {'FITS' if rep['fits'] else 'OVER'}"]
        for r in rows:
            kv = (f", kv {r['kv_bytes'] / 1e9:.3f} GB "
                  f"({r['kv_slots']}x{r['kv_seq']})"
                  if r["kv_bytes"] else "")
            lines.append(
                f"  {r['name']} [{r['engine']}] mesh {r['mesh']}: "
                f"peak {r['ff108_bytes'] / 1e9:.3f} GB (params "
                f"{r['params_bytes'] / 1e9:.3f} GB{kv})")
        text = "\n".join(lines)
    print(text)
    if args.out:
        import json as _json
        with open(args.out, "w") as f:
            f.write(_json.dumps(rep, indent=2) + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)
    return 0


def elastic_main(argv) -> int:
    """``flexflow-tpu elastic [flags] -- <script.py> [script args]``:
    run ``--nprocs`` copies of the script under the hardened elastic
    supervisor (flexflow_tpu/parallel/elastic.py) — heartbeat hang
    detection, failure classification, backoff-with-jitter restarts.

    Each worker gets ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES``
    / ``JAX_PROCESS_ID`` in its environment (fresh coordinator port per
    attempt), which ``initialize_distributed()`` — called by any script
    run through this CLI or flexflow_tpu directly — picks up.  Scripts
    resume via ``resilience.elastic_resume(model, workdir)``; the
    supervisor exports ``FF_ELASTIC_WORKDIR`` from ``--workdir``.
    Returns the process exit code (0 on recovered success)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="flexflow-tpu elastic",
        description="supervise an elastic multi-process training run")
    parser.add_argument("--nprocs", type=int, default=1,
                        help="worker processes per attempt")
    parser.add_argument("--max-restarts", type=int, default=2)
    parser.add_argument("--attempt-timeout", type=float, default=3600.0,
                        metavar="S")
    parser.add_argument("--hang-timeout", type=float, default=None,
                        metavar="S",
                        help="kill an attempt when no rank's heartbeat "
                             "step advances for S seconds (off unless "
                             "set; workers must beat via "
                             "flexflow_tpu.resilience.Heartbeat)")
    parser.add_argument("--workdir", default=".",
                        help="checkpoint directory exported to workers "
                             "as FF_ELASTIC_WORKDIR")
    parser.add_argument("--min-procs", type=int, default=None,
                        help="degrade-and-continue floor: after "
                             "--degrade-after consecutive crash/hang/"
                             "timeout attempts, HALVE the group (not "
                             "below this) and resume on the surviving "
                             "mesh instead of retrying the dead "
                             "topology (docs/elastic.md 'Resharding')")
    parser.add_argument("--degrade-after", type=int, default=2,
                        metavar="N",
                        help="consecutive topology-class failures "
                             "before a degrade step (default 2)")
    parser.add_argument("--backoff-base", type=float, default=0.5,
                        metavar="S")
    parser.add_argument("--backoff-max", type=float, default=30.0,
                        metavar="S")
    parser.add_argument("--backoff-seed", type=int, default=0)
    if "--" not in argv:
        parser.error("separate the worker script with '--': "
                     "flexflow-tpu elastic --nprocs 2 -- train.py -b 64")
    split = argv.index("--")
    args = parser.parse_args(argv[:split])
    worker_cmd = argv[split + 1:]
    if not worker_cmd:
        parser.error("no worker script given after '--'")

    from .parallel.elastic import run_elastic

    # a missing checkpoint dir would fail every attempt's first save
    os.makedirs(args.workdir, exist_ok=True)

    def worker_argv(attempt, port, rank):
        # through the CLI harness, not bare python: FlexFlow flags after
        # the script still parse into the default FFConfig, and main()'s
        # initialize_distributed() picks up the JAX_* env below
        return [sys.executable, "-m", "flexflow_tpu.cli", *worker_cmd]

    def per_rank_env(attempt, port, rank, nprocs):
        # nprocs is the CURRENT world size — the degrade policy may have
        # shrunk it below --nprocs; workers reshard on resume
        return {"JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
                "JAX_NUM_PROCESSES": str(nprocs),
                "JAX_PROCESS_ID": str(rank)}

    report = run_elastic(
        worker_argv, num_processes=args.nprocs,
        max_restarts=args.max_restarts,
        attempt_timeout_s=args.attempt_timeout,
        hang_timeout_s=args.hang_timeout,
        env={"FF_ELASTIC_WORKDIR": os.path.abspath(args.workdir)},
        per_rank_env=per_rank_env,
        backoff_base_s=args.backoff_base, backoff_max_s=args.backoff_max,
        backoff_seed=args.backoff_seed,
        min_processes=args.min_procs, degrade_after=args.degrade_after)
    for i, a in enumerate(report.attempts):
        steps = (" steps=" + ",".join(
            f"r{r}:{s}" for r, s in sorted(a.rank_steps.items()))
            if a.rank_steps else "")
        detail = f" ({a.spawn_error})" if a.spawn_error else ""
        print(f"elastic attempt {i}: cause={a.cause} "
              f"nprocs={a.num_processes} "
              f"rc={a.returncodes} elapsed={a.elapsed_s}s"
              f"{steps}{detail}", file=sys.stderr)
        if a.cause != "ok" and a.failed_rank is not None:
            tail = a.tails.get(a.failed_rank, "").strip()
            if tail:
                print(f"  rank {a.failed_rank} tail: ...{tail[-400:]}",
                      file=sys.stderr)
    if report.success:
        print(f"elastic: success after {report.restarts} restart(s)",
              file=sys.stderr)
        return 0
    print("elastic: FAILED"
          + (" (fail-fast: instant all-rank crash on attempt 0 — "
             "likely an argv/config error)" if report.fail_fast else
             f" after {len(report.attempts)} attempt(s)"),
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    main()
