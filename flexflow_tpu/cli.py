"""``flexflow-tpu`` console entry — the reference's ``flexflow_python``
runner (python/Makefile, flexflow_top.py:164-220): parses the FlexFlow flag
set into an FFConfig, installs it as the process default, and executes the
user script.

    flexflow-tpu my_model.py -b 64 -e 10 --lr 0.01 -ll:tpu 8 --budget 500

Where the reference launches the script as a Legion top-level task, here the
script simply runs under CPython with ``FFConfig.parse_args``'s result made
available via :func:`flexflow_tpu.get_default_config` (scripts may also call
``FFConfig.parse_args()`` themselves, same flags)."""

from __future__ import annotations

import runpy
import sys

from .config import FFConfig


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    # built-in subcommands (no user script involved)
    if argv and argv[0] == "search-bench":
        # search-throughput microbenchmark: delta vs full re-simulation
        # (JSON to stdout; see docs/strategy_search.md)
        from .search.bench import main as bench_main
        bench_main(argv[1:])
        return
    script = None
    for a in argv:
        if a.endswith(".py"):
            script = a
            break
    if script is None:
        print("usage: flexflow-tpu <script.py> [FlexFlow flags]\n"
              "flags (reference model.cc:1221-1289): -e -b --lr --wd -d "
              "--budget --alpha -s/-import -ll:tpu -ll:cpu --nodes "
              "--profiling --seed --remat", file=sys.stderr)
        raise SystemExit(2)
    flags = [a for a in argv if a != script]
    cfg = FFConfig.parse_args(flags)
    import flexflow_tpu
    flexflow_tpu.set_default_config(cfg)
    # bring up the multi-host runtime when this is one process of a slice
    # (single-process runs are a no-op) — the reference's GASNet bring-up
    # happens likewise before the top-level task runs.  --nodes > 1 makes
    # the multi-host requirement explicit: failing to form the world is an
    # error, not N disconnected replicas.
    from flexflow_tpu.parallel import initialize_distributed
    initialize_distributed(
        num_processes=cfg.num_nodes if cfg.num_nodes > 1 else None)
    # the script sees the remaining argv like any __main__
    sys.argv = [script] + flags
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
