"""Multi-host distributed runtime (the reference's GASNet/multi-node path:
FlexFlow.mk:68-69, DLRM run_summit scripts).

TPU-native: each host runs the same program (multi-controller SPMD);
``initialize_distributed`` brings up JAX's coordination service, after which
``jax.devices()`` spans every chip in the slice and a MachineMesh built over
it shards across hosts — XLA routes collectives over ICI within a slice and
DCN across slices.  Where the reference's mapper steers region placement
per node (mapper.cc:268-365), here placement falls out of the global mesh.

Single-process runs (and the CPU test mesh) skip initialization and behave
identically, so the same script scales from 1 chip to a pod without change:

    flexflow-tpu train.py --nodes 4 -ll:tpu 4   # on each host
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Initialize the multi-host runtime.  Arguments default to the standard
    environment (TPU metadata or JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID).  Returns True when a multi-process
    runtime came up, False for the single-process no-op."""
    if num_processes is None:
        env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if process_id is None:
        env = os.environ.get("JAX_PROCESS_ID")
        process_id = int(env) if env else None
    # TPU_WORKER_HOSTNAMES lists the slice's hosts; a single entry (or the
    # var's absence) means single-process — nothing to initialize
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    multi_host_tpu = "," in hostnames
    if (coordinator_address is None and num_processes is None
            and not multi_host_tpu):
        return False
    if (num_processes is not None and num_processes > 1
            and coordinator_address is None and not multi_host_tpu):
        # multi-host explicitly requested but unreachable: fail loudly
        # rather than training N disconnected replicas
        raise ValueError(
            f"{num_processes} processes requested but no coordinator is "
            f"configured — set JAX_COORDINATOR_ADDRESS (+ JAX_PROCESS_ID) "
            f"on every host, or run on a TPU slice with worker metadata")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def coordination_barrier(name: str = "ff_barrier",
                         timeout_s: int = 900) -> None:
    """Host-level barrier through the coordination service (single-process
    no-op).  Unlike a device collective this is usable BEFORE the first
    program executes: the CPU/TPU collective context is set up lazily at
    first execution with a short (~30 s) rendezvous deadline, so when
    per-process compile times are skewed (cold caches, contended hosts)
    the fast processes must wait HERE, not in the rendezvous.  The
    reference reaches the same global quiescence with
    ``runtime->issue_execution_fence`` between phases."""
    if jax.process_count() <= 1:
        return
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is not None:
        client.wait_at_barrier(name, timeout_in_ms=timeout_s * 1000)


def finalize_distributed() -> None:
    """Tear down the multi-host runtime (single-process no-op).

    Synchronizes every process with a device-level barrier BEFORE asking
    the coordination service to shut down: the service's shutdown
    barrier has a short (~30 s) deadline, and on a contended host a
    straggler — still flushing checkpoints or garbage-collecting — can
    miss it, poisoning every other process with a fatal
    ``Shutdown barrier has failed``.  The sync has no such deadline, so
    all processes arrive at the shutdown barrier together.  Mirrors the
    reference's explicit runtime teardown at the end of top_level_task.
    """
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("flexflow_tpu_finalize")
    jax.distributed.shutdown()


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
