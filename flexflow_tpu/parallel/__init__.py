from .distributed import initialize_distributed, process_info
from .mesh import AXES, MachineMesh, dim_axis_names
from .sharding import batch_spec, output_spec, param_spec
