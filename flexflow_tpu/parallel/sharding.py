"""ParallelConfig -> jax.sharding translation.

This is the TPU replacement for the reference's partition builders
(``create_tensor<NDIM>`` model.cc:437-506, ``create_linear_weight``
model.cc:582-669, ``create_linear_replica`` model.cc:762-817): instead of
materializing Legion partition trees, each op's resolved ParallelConfig
becomes a ``PartitionSpec`` constraint on its output, and each Parameter gets
a NamedSharding.  GSPMD then inserts the collectives the reference got from
Legion region movement (producer/consumer partition mismatch -> resharding;
TP partial-grad replicas -> psum; DP grad replicas -> psum in backward).

Mesh-expressibility contract (SURVEY §7 "hard parts"): a config degree for
logical dim i must be a divisor of the mesh axis size for that dim's
canonical axis — the mesh factors each axis into prime sub-axes
(mesh.MachineMesh), so any divisor degree maps to a sub-axis subset; a
degree that is NOT a realizable divisor falls back to replication instead
of crashing the trace (a strategy file from the reference may encode
placements GSPMD cannot express; running them replicated is the honest
degrade).  Fallbacks are RECORDED as verifier diagnostics
(analysis.record_replicate_fallback, aggregated per site — tracing
revisits a tensor many times) instead of warned per traced tensor; the
static verifier predicts the same set at compile time from the same
predicate (analysis.legality.degree_executable), so
``FFModel.compile(verify="warn")`` surfaces them once, with a count.
"""

from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec

from ..config import ParallelConfig
from ..tensor import Parameter, Tensor
from .mesh import MachineMesh, dim_axis_names


def _record_fallback(name: str, dim: int, degree: int, axis,
                     axis_size: int, reason: str) -> None:
    # lazy import: analysis pulls in the op/cost layers and this module
    # loads early in the package graph
    from ..analysis.verifier import record_replicate_fallback
    record_replicate_fallback(name, dim, degree, axis, axis_size, reason)


def dim_entry(extent: int, dim: int, degree: int, axis, mesh,
              name: str, on_fallback) -> object:
    """THE per-dim placement decision: the PartitionSpec entry one
    logical dim gets for a requested ``degree`` on ``axis``, or None
    with ``on_fallback(name, dim, degree, axis, axis_size, reason)``
    fired when the executor replicates instead.  Shared verbatim by the
    trace-time builders below and the static verifier's sharding pass
    (``analysis/sharding_passes.py``) — ``mesh`` may be a
    :class:`~flexflow_tpu.parallel.mesh.MachineMesh` (trace) or a
    device-free :class:`~flexflow_tpu.parallel.mesh.AbstractMesh`
    (lint/explain); both answer ``axis_size``/``axis_spec`` with the
    same :class:`~flexflow_tpu.parallel.mesh._MeshAxes` math, so the
    static FF120 prediction and the runtime FF106 record cannot
    diverge."""
    if degree <= 1:
        return None
    size = mesh.axis_size(axis) if axis else 1
    sub = mesh.axis_spec(axis, degree) if axis else None
    from ..analysis.legality import degree_executable
    # the ONE legality predicate (analysis.legality), shared with the
    # SOAP search and the static verifier; the mesh's own axis_spec
    # answer is passed in so expressibility is decided (and searched)
    # exactly once per dim
    reason = degree_executable(extent, degree, size, axis,
                               expressible=sub is not None)
    if reason is not None:
        on_fallback(name, dim, degree, axis, size, reason)
        return None
    return axis if degree == size else sub


def output_spec(tensor: Tensor, pc: Optional[ParallelConfig],
                mesh, on_fallback=None) -> PartitionSpec:
    """PartitionSpec for an op output under its ParallelConfig.
    ``on_fallback`` overrides the runtime replicate-fallback recorder
    (FF106) — the static pass passes its own collector."""
    if on_fallback is None:
        on_fallback = _record_fallback
    rank = tensor.num_dims
    axes = dim_axis_names(rank)
    if pc is None:
        # replicate-by-default except sample dim over 'n'
        entries = ["n" if (rank > 1 and i == 0 and mesh.axis_size("n") > 1
                           and tensor.shape[0] % mesh.axis_size("n") == 0)
                   else None for i in range(rank)]
        return PartitionSpec(*entries)
    dims = pc.dims
    if len(dims) != rank:
        dims = tuple(dims[:rank]) + (1,) * max(0, rank - len(dims))
    entries = [dim_entry(tensor.shape[i], i, deg, ax, mesh,
                         tensor.name, on_fallback)
               for i, (deg, ax) in enumerate(zip(dims, axes))]
    return PartitionSpec(*entries)


def param_spec(param: Parameter, pc: Optional[ParallelConfig],
               mesh, on_fallback=None) -> PartitionSpec:
    """Weight sharding.  DP weights are replicated (the reference keeps one
    logical weight region with per-replica grads); a channel-parallel op
    shards its weight on ``sharded_dim`` over axis 'c'
    (reference create_linear_weight, model.cc:582-669); pipeline-stacked
    weights (shard_axis 'p') always shard their stage dim over 'p'.
    ``on_fallback`` as in :func:`output_spec`."""
    if on_fallback is None:
        on_fallback = _record_fallback
    if param.shard_axis in ("p", "e"):
        # stage-stacked (pipeline) / expert-stacked (MoE) weights shard
        # their leading stack dim over the dedicated mesh axis
        entries = [None] * len(param.shape)
        if (param.sharded_dim is not None
                and mesh.axis_size(param.shard_axis) > 1):
            entries[param.sharded_dim] = param.shard_axis
        # a pipeline-stacked weight may carry a SECOND in-stage sharding
        # (c-TP linear or e-stacked MoE expert dim inside a stage) — the
        # {n,c,e,p} composition
        idim = param.inner_sharded_dim
        if (idim is not None and idim < len(param.shape)
                and mesh.axis_size(param.inner_shard_axis) > 1
                and param.shape[idim] % mesh.axis_size(
                    param.inner_shard_axis) == 0
                and entries[idim] is None):
            entries[idim] = param.inner_shard_axis
        if any(e is not None for e in entries):
            return PartitionSpec(*entries)
        return PartitionSpec()
    if (pc is None or param.sharded_dim is None
            or mesh.axis_size("c") <= 1):
        return PartitionSpec()
    # channel degree sits at the canonical 'c' position of the *output*
    rank = len(pc.dims)
    axes = dim_axis_names(rank)
    c_deg = 1
    for deg, ax in zip(pc.dims, axes):
        if ax == "c":
            c_deg = deg
    if c_deg <= 1:
        return PartitionSpec()
    entry = dim_entry(param.shape[param.sharded_dim], param.sharded_dim,
                      c_deg, "c", mesh, param.name, on_fallback)
    if entry is None:
        return PartitionSpec()
    entries = [None] * len(param.shape)
    entries[param.sharded_dim] = entry
    return PartitionSpec(*entries)


def batch_spec(rank: int, mesh: MachineMesh,
               seq_sharded: bool = False) -> PartitionSpec:
    """Input-batch sharding: sample dim over 'n' (the reference dataloader's
    batch partition, flexflow_dataloader.cc:260-330), optional sequence dim
    over 's' for context parallelism."""
    entries: list = [None] * rank
    if rank >= 1 and mesh.axis_size("n") > 1:
        entries[0] = "n"
    if seq_sharded and rank >= 2 and mesh.axis_size("s") > 1:
        entries[1] = "s"
    return PartitionSpec(*entries)
