"""MachineMesh — the TPU device-mesh placement layer.

Replaces the reference's FFMapper (``src/mapper/mapper.cc``,
``include/mapper.h:26-62``): where the mapper binds each Legion task slice to
a GPU processor via per-op ``ParallelConfig`` lookups (mapper.cc:33-146), we
bind logical partition axes to named mesh axes over the ICI fabric and let
GSPMD place shards.  The five canonical axes mirror the SOAP dimensions:

====  ==========================================================
axis  meaning
====  ==========================================================
n     sample / batch (data parallelism)
c     channel (tensor/model parallelism — Linear out-dim, §2.15)
h,w   spatial attribute parallelism (conv h/w splits)
s     sequence (sequence/context parallelism — new axis; the
      reference's only sequence partitioning is NMT timestep
      chunking, nmt/rnn.h:23)
====  ==========================================================

Axes of size 1 cost nothing; a plain data-parallel run is mesh ``{"n": N}``.
The reference's ``% devices.size()`` wrap-around (mapper.cc:86-103) — running
an 8-part strategy on fewer GPUs — maps to testing big meshes on 8 virtual
CPU devices via ``--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES: Tuple[str, ...] = ("n", "c", "h", "w", "s")

# readable aliases accepted in mesh_shape configs
_ALIAS = {"data": "n", "batch": "n", "model": "c", "tensor": "c",
          "seq": "s", "sequence": "s", "expert": "c", "pipeline": "h"}


class MachineMesh:
    """A named jax Mesh over the visible devices (or an explicit list)."""

    def __init__(self, shape: Optional[Dict[str, int]] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        devices = list(devices if devices is not None else jax.devices())
        sizes = {a: 1 for a in AXES}
        if shape:
            for k, v in shape.items():
                sizes[_ALIAS.get(k, k)] = int(v)
        used = int(np.prod(list(sizes.values())))
        if used == 1 and len(devices) > 1 and not shape:
            sizes["n"] = len(devices)  # default: pure data parallel
            used = len(devices)
        if used > len(devices):
            raise ValueError(f"mesh {sizes} needs {used} devices, "
                             f"have {len(devices)}")
        devices = devices[:used]
        dev_array = np.array(devices).reshape([sizes[a] for a in AXES])
        self.sizes = sizes
        self.mesh = Mesh(dev_array, AXES)
        self.num_devices = used

    @property
    def is_distributed(self) -> bool:
        return self.num_devices > 1

    def axis_size(self, axis: str) -> int:
        return self.sizes[_ALIAS.get(axis, axis)]

    def sharding(self, spec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self) -> str:
        live = {a: s for a, s in self.sizes.items() if s > 1}
        return f"MachineMesh({live or {'n': 1}}, devices={self.num_devices})"


def dim_axis_names(rank: int) -> Tuple[Optional[str], ...]:
    """Canonical logical-dim -> mesh-axis assignment by tensor rank.

    rank 4 = conv activations (n,c,h,w); rank 3 = sequence activations
    (n,s,c); rank 2 = (n,c); rank 1 = (c,).
    """
    if rank == 4:
        return ("n", "c", "h", "w")
    if rank == 3:
        return ("n", "s", "c")
    if rank == 2:
        return ("n", "c")
    if rank == 1:
        return ("c",)
    return tuple([None] * rank)
