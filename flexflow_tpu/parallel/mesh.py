"""MachineMesh — the TPU device-mesh placement layer.

Replaces the reference's FFMapper (``src/mapper/mapper.cc``,
``include/mapper.h:26-62``): where the mapper binds each Legion task slice to
a GPU processor via per-op ``ParallelConfig`` lookups (mapper.cc:33-146), we
bind logical partition axes to named mesh axes over the ICI fabric and let
GSPMD place shards.  The five canonical axes mirror the SOAP dimensions:

====  ==========================================================
axis  meaning
====  ==========================================================
n     sample / batch (data parallelism)
c     channel (tensor/model parallelism — Linear out-dim, §2.15)
h,w   spatial attribute parallelism (conv h/w splits)
s     sequence (sequence/context parallelism — new axis; the
      reference's only sequence partitioning is NMT timestep
      chunking, nmt/rnn.h:23)
====  ==========================================================

Axes of size 1 cost nothing; a plain data-parallel run is mesh ``{"n": N}``.
The reference's ``% devices.size()`` wrap-around (mapper.cc:86-103) — running
an 8-part strategy on fewer GPUs — maps to testing big meshes on 8 virtual
CPU devices via ``--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES: Tuple[str, ...] = ("n", "c", "h", "w", "s", "e", "p")

# readable aliases accepted in mesh_shape configs.  "p" (pipeline stages)
# and "e" (experts) map to no logical tensor dim (dim_axis_names never
# yields them) — pipeline stages shard stacked weights over "p" with
# activations on a ppermute ring; MoE expert weights shard over "e" with
# token dispatch riding GSPMD's all_to_all.
_ALIAS = {"data": "n", "batch": "n", "model": "c", "tensor": "c",
          "seq": "s", "sequence": "s", "expert": "e", "pipeline": "p",
          "stage": "p"}


def prime_factors(n: int) -> Tuple[int, ...]:
    """Ascending prime factorization (with multiplicity)."""
    out = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return tuple(out)


def subset_for_degree(factors: Sequence[int], degree: int):
    """Indices of a sub-multiset of ``factors`` whose product == degree,
    preferring a prefix (keeps producer/consumer shardings aligned).
    Returns None when no subset works."""
    if degree == 1:
        return ()
    prod, pref = 1, []
    for i, f in enumerate(factors):
        prod *= f
        pref.append(i)
        if prod == degree:
            return tuple(pref)
        if prod > degree:
            break
    # general subset DFS
    def dfs(i, rem, picked):
        if rem == 1:
            return tuple(picked)
        if i >= len(factors):
            return None
        if rem % factors[i] == 0:
            r = dfs(i + 1, rem // factors[i], picked + [i])
            if r is not None:
                return r
        return dfs(i + 1, rem, picked)

    return dfs(0, degree, [])


def expressible_degrees(size: int) -> Tuple[int, ...]:
    """All degrees realizable as sub-multiset products of size's primes
    (== all divisors of ``size``), ascending."""
    factors = prime_factors(size)
    degs = {1}
    for f in factors:
        degs |= {d * f for d in degs}
    return tuple(sorted(degs))


def degree_expressible(axis_size: int, degree: int) -> bool:
    """THE mesh-expressibility predicate: can ``degree`` shards map onto a
    sub-axis subset of an axis of ``axis_size``?  This is exactly the
    decision :meth:`MachineMesh.axis_spec` makes at trace time (same
    ``subset_for_degree`` core), exported so the static verifier
    (``flexflow_tpu.analysis``) and the SOAP search judge legality with
    the GSPMD-reality predicate instead of a reimplementation."""
    if degree <= 1:
        return True
    return subset_for_degree(prime_factors(axis_size), degree) is not None


class _MeshAxes:
    """The axis MATH shared by :class:`MachineMesh` (trace time, owns a
    jax Mesh over real devices) and :class:`AbstractMesh` (the static
    verifier's device-free view): canonical-axis sizes, their prime
    sub-axis factorization, and the degree -> sub-axis-subset decision
    (:meth:`axis_spec`).  One implementation means the static sharding
    pass (``analysis/sharding_passes.py``) and the tracer CANNOT diverge
    on which degrees are realizable — they literally run the same code.
    """

    def _init_axes(self, sizes: Dict[str, int]) -> None:
        self.sizes = sizes
        self._subaxes: Dict[str, Tuple[str, ...]] = {}
        self._subfactors: Dict[str, Tuple[int, ...]] = {}
        for a in AXES:
            fs = prime_factors(sizes[a]) if sizes[a] > 1 else ()
            self._subaxes[a] = tuple(f"{a}{i}" for i in range(len(fs)))
            self._subfactors[a] = fs
        # the MESH product — distinct from num_devices on an
        # AbstractMesh whose machine is larger than the mesh
        self.mesh_product = int(np.prod(list(sizes.values())))
        self.num_devices = self.mesh_product

    @property
    def is_distributed(self) -> bool:
        # keyed on the mesh product, NOT the machine size: a {'n': 1}
        # mesh on an 8-device machine constrains nothing at trace time,
        # and the static pass must mirror that exactly
        return self.mesh_product > 1

    def axis_size(self, axis: str) -> int:
        return self.sizes[_ALIAS.get(axis, axis)]

    def subaxes(self, axis: str) -> Tuple[str, ...]:
        """The prime sub-axis names materializing a canonical axis."""
        return self._subaxes.get(_ALIAS.get(axis, axis), ())

    def axis_spec(self, axis: str, degree: int):
        """Sub-axis name tuple realizing ``degree`` shards on ``axis``;
        the full canonical name when degree == axis size; None when the
        degree is not a realizable divisor."""
        a = _ALIAS.get(axis, axis)
        if degree <= 1:
            return ()
        if degree == self.sizes[a]:
            return self._subaxes[a]
        idx = subset_for_degree(self._subfactors[a], degree)
        if idx is None:
            return None
        return tuple(self._subaxes[a][i] for i in idx)


class AbstractMesh(_MeshAxes):
    """A mesh SHAPE without devices — the static verifier's machine view.

    Shares every axis decision with :class:`MachineMesh` via
    :class:`_MeshAxes` but never touches jax, so a 64-chip mesh spec can
    be interpreted on a CPU-only laptop (``flexflow-tpu explain``, the
    FF120 fallback prediction).  ``num_devices`` may exceed the mesh
    product (a machine bigger than the strategy uses); it never needs to
    exist."""

    def __init__(self, shape: Optional[Dict[str, int]] = None,
                 num_devices: Optional[int] = None):
        sizes = {a: 1 for a in AXES}
        for k, v in (shape or {}).items():
            a = _ALIAS.get(k, k)
            if a not in sizes:
                # fail like the runtime would, with a better message: a
                # typo'd axis must not produce a confidently wrong
                # static report (every canonical axis silently size 1)
                raise ValueError(
                    f"unknown mesh axis {k!r} (canonical axes: "
                    f"{', '.join(AXES)}; aliases: "
                    f"{', '.join(sorted(_ALIAS))})")
            sizes[a] = int(v)
        self._init_axes(sizes)
        if num_devices is not None:
            if num_devices < self.num_devices:
                raise ValueError(
                    f"mesh {sizes} needs {self.num_devices} devices, "
                    f"machine has {num_devices}")
            self.num_devices = int(num_devices)

    def __repr__(self) -> str:
        live = {a: s for a, s in self.sizes.items() if s > 1}
        return (f"AbstractMesh({live or {'n': 1}}, "
                f"devices={self.num_devices})")


class MachineMesh(_MeshAxes):
    """A named jax Mesh over the visible devices (or an explicit list).

    Each canonical axis is materialized as its prime-factor *sub-axes*
    (axis "n" of size 8 -> mesh axes n0,n1,n2 of size 2 each), so an op may
    shard a dim with ANY divisor degree of the axis size — the mixed
    per-op degrees of SOAP strategies (reference
    Op::get_random_parallel_config, model.cc:276-305) map to sub-axis
    subsets instead of being rejected.  A PartitionSpec entry that names a
    canonical axis is expanded to all its sub-axes by :meth:`sharding`.
    """

    def __init__(self, shape: Optional[Dict[str, int]] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        devices = list(devices if devices is not None else jax.devices())
        sizes = {a: 1 for a in AXES}
        if shape:
            for k, v in shape.items():
                a = _ALIAS.get(k, k)
                if a not in sizes:
                    # same loud failure as AbstractMesh: an unknown axis
                    # used to die later as an opaque reshape error
                    raise ValueError(
                        f"unknown mesh axis {k!r} (canonical axes: "
                        f"{', '.join(AXES)}; aliases: "
                        f"{', '.join(sorted(_ALIAS))})")
                sizes[a] = int(v)
        used = int(np.prod(list(sizes.values())))
        if used == 1 and len(devices) > 1 and not shape:
            sizes["n"] = len(devices)  # default: pure data parallel
            used = len(devices)
        if used > len(devices):
            raise ValueError(f"mesh {sizes} needs {used} devices, "
                             f"have {len(devices)}")
        devices = devices[:used]
        self._init_axes(sizes)
        names: list = []
        dims: list = []
        for a in AXES:
            names.extend(self._subaxes[a])
            dims.extend(self._subfactors[a])
        if not names:  # single device still needs a valid Mesh
            names, dims = ["n0"], [1]
            self._subaxes["n"] = ("n0",)
            self._subfactors["n"] = (1,)
        dev_array = np.array(devices).reshape(dims)
        self.mesh = Mesh(dev_array, tuple(names))
        self.num_devices = self.mesh_product = used

    def _expand(self, entry):
        if entry is None:
            return None
        if isinstance(entry, str):
            subs = self._subaxes.get(_ALIAS.get(entry, entry))
            if subs is not None:  # canonical axis name -> all sub-axes
                return subs if len(subs) > 0 else None
            return entry  # already a sub-axis name
        return tuple(entry) or None

    def sharding(self, spec: PartitionSpec) -> NamedSharding:
        entries = tuple(self._expand(e) for e in spec)
        return NamedSharding(self.mesh, PartitionSpec(*entries))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self) -> str:
        live = {a: s for a, s in self.sizes.items() if s > 1}
        return f"MachineMesh({live or {'n': 1}}, devices={self.num_devices})"


def scaled_shape(sizes: Dict[str, int], num_devices: int) -> Dict[str, int]:
    """Rescale a mesh's axis sizes to a new device count by resizing the
    data axis ``n`` and keeping every other live axis — the default
    grow/shrink policy of the elastic reshard path (``FFModel.reshard``
    and the ``grow_at_step``/``shrink_at_step`` fault kinds): model/
    sequence/expert parallel degrees are properties of the strategy, so
    a capacity change lands on the data axis unless a re-search says
    otherwise.  Raises when the surviving non-``n`` product does not
    divide ``num_devices`` (e.g. shrinking a {n:2, c:4} mesh to 2
    devices needs a real re-search, not an axis rescale)."""
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    other = 1
    for a, s in sizes.items():
        if a != "n" and s > 1:
            other *= int(s)
    if num_devices % other:
        raise ValueError(
            f"cannot rescale mesh {dict(sizes)} to {num_devices} "
            f"device(s): the non-'n' axes use {other} which does not "
            f"divide it — reshard with an explicit mesh (or re-search)")
    shape = {a: int(s) for a, s in sizes.items() if a != "n" and s > 1}
    shape["n"] = num_devices // other
    return shape


def dim_axis_names(rank: int) -> Tuple[Optional[str], ...]:
    """Canonical logical-dim -> mesh-axis assignment by tensor rank.

    rank 4 = conv activations (n,c,h,w); rank 3 = sequence activations
    (n,s,c); rank 2 = (n,c); rank 1 = (c,).
    """
    if rank == 4:
        return ("n", "c", "h", "w")
    if rank == 3:
        return ("n", "s", "c")
    if rank == 2:
        return ("n", "c")
    if rank == 1:
        return ("c",)
    return tuple([None] * rank)
