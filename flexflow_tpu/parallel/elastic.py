"""Elastic multi-process training: failure detection + restart-from-
checkpoint.

Neither the reference nor Legion provides worker-failure recovery
(SURVEY §5: failure detection "absent entirely" — a dead GASNet rank
kills the job).  The TPU-native stack makes the recovery loop small
enough to own: jax.distributed workers are ordinary OS processes, the
sharding-aware checkpoint (`FFModel.save_checkpoint`) captures params +
optimizer state + step on process 0, and a restarted group re-forms the
global mesh from scratch.  This launcher supervises the group:

  * spawn N worker processes (fresh coordinator port per attempt — a
    dead gloo context cannot be rejoined);
  * poll liveness; ANY worker exiting nonzero (or the attempt timing
    out) fails the attempt — remaining workers are killed and reaped,
    mirroring the all-or-nothing semantics of a jax.distributed group;
  * relaunch up to ``max_restarts`` times.  Workers are responsible for
    resuming: the standard pattern is "load the newest checkpoint if one
    exists, else start fresh" (tests/_elastic_worker.py demonstrates it
    and tests/test_elastic.py pins exact loss parity with an
    uninterrupted run).

Deliberately process-level: hung-worker detection is the attempt
timeout, not an in-band heartbeat — a wedged XLA collective cannot be
observed from inside the process anyway (the same reasoning as
bench.py's killable-subprocess probe).
"""

from __future__ import annotations

import dataclasses
import os
import socket
import subprocess
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class AttemptResult:
    port: int
    returncodes: List[Optional[int]]
    failed_rank: Optional[int]  # first rank observed dead/nonzero
    timed_out: bool
    elapsed_s: float
    tails: Dict[int, str]       # rank -> tail of combined stdout+stderr log
    # transient OSError from Popen while spawning (ADVICE r5): recorded
    # so the failure consumes a restart instead of aborting supervision
    spawn_error: Optional[str] = None


@dataclasses.dataclass
class ElasticReport:
    success: bool
    attempts: List[AttemptResult]

    @property
    def restarts(self) -> int:
        return max(0, len(self.attempts) - 1)


def run_elastic(worker_argv: Callable[[int, int, int], Sequence[str]],
                num_processes: int,
                max_restarts: int = 2,
                attempt_timeout_s: float = 600.0,
                poll_interval_s: float = 0.5,
                env: Optional[Dict[str, str]] = None,
                grace_kill_s: float = 5.0) -> ElasticReport:
    """Supervise ``num_processes`` workers; restart the whole group on
    any failure, at most ``max_restarts`` times.

    ``worker_argv(attempt, port, rank)`` builds each worker's argv; the
    coordinator port is fresh per attempt.  ``env`` extends (not
    replaces) os.environ; the launcher additionally exports
    ``FF_ELASTIC_ATTEMPT`` so failure-injection tests can target one
    attempt.  Returns an :class:`ElasticReport`; ``success`` means some
    attempt had every worker exit 0."""
    attempts: List[AttemptResult] = []
    for attempt in range(max_restarts + 1):
        port = free_port()
        worker_env = dict(os.environ)
        if env:
            worker_env.update(env)
        worker_env["FF_ELASTIC_ATTEMPT"] = str(attempt)
        procs: List[subprocess.Popen] = []
        # per-rank log FILES, not pipes: an undrained pipe blocks the
        # worker after ~64 KB of output (a verbose XLA warning dump
        # would masquerade as a hang and burn an attempt)
        logdir = tempfile.mkdtemp(prefix=f"ff_elastic_a{attempt}_")
        logs = []
        t0 = time.monotonic()
        failed_rank: Optional[int] = None
        timed_out = False
        spawn_error: Optional[str] = None
        try:
            # a transient OSError (fd exhaustion, ENOMEM, a briefly
            # missing interpreter on shared storage) from open/Popen is
            # an attempt FAILURE, not a supervision abort: record it,
            # reap whatever spawned, and let the restart loop retry
            try:
                for rank in range(num_processes):
                    lf = open(os.path.join(logdir, f"rank{rank}.log"),
                              "w+b")
                    logs.append(lf)
                    procs.append(subprocess.Popen(
                        list(worker_argv(attempt, port, rank)),
                        stdout=lf, stderr=subprocess.STDOUT,
                        env=worker_env))
            except OSError as e:
                failed_rank = len(procs)  # the rank that failed to spawn
                spawn_error = f"{type(e).__name__}: {e}"
            while spawn_error is None:
                codes = [p.poll() for p in procs]
                bad = [r for r, c in enumerate(codes)
                       if c is not None and c != 0]
                if bad:
                    failed_rank = bad[0]
                    break
                if all(c == 0 for c in codes):
                    break
                if time.monotonic() - t0 > attempt_timeout_s:
                    timed_out = True
                    break
                time.sleep(poll_interval_s)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            deadline = time.monotonic() + grace_kill_s
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        tails = {}
        for r, lf in enumerate(logs):
            try:
                lf.flush()
                lf.seek(0, os.SEEK_END)
                size = lf.tell()
                lf.seek(max(0, size - 800))
                tails[r] = lf.read().decode("utf-8", "replace")
            except Exception:
                tails[r] = "<log unavailable>"
            finally:
                lf.close()
        result = AttemptResult(
            port=port,
            returncodes=[p.returncode for p in procs],
            failed_rank=failed_rank, timed_out=timed_out,
            elapsed_s=round(time.monotonic() - t0, 3), tails=tails,
            spawn_error=spawn_error)
        attempts.append(result)
        if not timed_out and failed_rank is None \
                and all(c == 0 for c in result.returncodes):
            return ElasticReport(True, attempts)
    return ElasticReport(False, attempts)


def latest_checkpoint(directory: str, prefix: str = "elastic") -> Optional[str]:
    """Newest ``<prefix>_step*.npz`` checkpoint in ``directory`` (the
    worker-side half of the resume pattern), or None.  Sorted by the
    step number embedded in the name, not mtime — ranks may observe
    different mtimes on shared storage."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    best, best_step = None, -1
    for n in names:
        if not (n.startswith(prefix + "_step") and n.endswith(".npz")):
            continue
        try:
            step = int(n[len(prefix + "_step"):-len(".npz")])
        except ValueError:
            continue
        if step > best_step:
            best, best_step = n, step
    return os.path.join(directory, best) if best else None
