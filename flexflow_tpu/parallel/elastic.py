"""Elastic multi-process training: failure detection + restart-from-
checkpoint, hardened with heartbeats, failure classification and a
restart policy.

Neither the reference nor Legion provides worker-failure recovery
(SURVEY §5: failure detection "absent entirely" — a dead GASNet rank
kills the job).  The TPU-native stack makes the recovery loop small
enough to own: jax.distributed workers are ordinary OS processes, the
sharding-aware checkpoint (`FFModel.save_checkpoint`) captures params +
optimizer state + step on process 0, and a restarted group re-forms the
global mesh from scratch.  This launcher supervises the group:

  * spawn N worker processes (fresh coordinator port per attempt — a
    dead gloo context cannot be rejoined; the previous attempt's port is
    never handed out again, and a coordinator "address already in use"
    in a worker tail is classified as a ``spawn``-class transient);
  * poll liveness AND progress: ANY worker exiting nonzero fails the
    attempt (all-or-nothing, mirroring a jax.distributed group), and
    when heartbeats are enabled (``hang_timeout_s``) an attempt in which
    *no* rank advances its step for that long is killed early and
    classified ``hung`` — a wedged XLA collective no longer burns the
    full ``attempt_timeout_s``;
  * classify every failed attempt (``crash`` / ``hung`` / ``spawn`` /
    ``timeout``) and relaunch up to ``max_restarts`` times with
    exponential backoff + seeded jitter between attempts.  A first
    attempt in which EVERY rank exits nonzero essentially instantly
    fails fast instead — an argv/config typo should not burn all
    restarts (spawn-class failures never trip this);
  * workers are responsible for resuming: the standard pattern is "load
    the newest VALID checkpoint if one exists, else start fresh"
    (:func:`latest_valid_checkpoint` / ``resilience.elastic_resume``;
    tests/_elastic_worker.py demonstrates it and tests/test_elastic.py +
    tests/test_faults.py pin every recovery path under injected faults —
    see flexflow_tpu/faults.py and docs/elastic.md).

Heartbeat protocol: the supervisor exports ``FF_HEARTBEAT_DIR`` (fresh
per attempt); each rank stamps ``rank<r>.hb`` with its step via
``resilience.Heartbeat``.  The monitor compares successive directory
snapshots with its *own* clock — worker clocks are never compared.
Detection starts at the first observed beat, so long cold compiles
before step 0 are covered by ``attempt_timeout_s``, not mistaken for
hangs.  Final per-rank steps are recorded in ``AttemptResult.rank_steps``
(straggler forensics) even when hang detection is off.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
import random
import socket
import subprocess
import tempfile
import time
from typing import (Callable, Collection, Dict, List, Mapping, Optional,
                    Sequence)

from ..faults import spawn_fail_requested
from ..resilience import read_heartbeats


def _call_sized(fn, attempt: int, port: int, rank: int, nprocs: int):
    """Invoke a worker_argv/per_rank_env callback with the CURRENT world
    size as a 4th argument when the callable accepts one — the degrade
    policy can shrink the group between attempts, and a worker spawned
    into the smaller world must be told its size.  3-arg callables (the
    original contract, and every pre-degrade caller) keep working: only
    a 4th REQUIRED positional opts in — defaulted extras (a 3-arg
    callable with its own optional parameters) and ``*args`` catch-alls
    stay on the legacy call, so nprocs never lands in an unrelated
    parameter."""
    try:
        params = inspect.signature(fn).parameters.values()
        nargs = sum(1 for p in params
                    if p.kind in (p.POSITIONAL_ONLY,
                                  p.POSITIONAL_OR_KEYWORD)
                    and p.default is p.empty)
    except (TypeError, ValueError):
        nargs = 3
    if nargs >= 4:
        return fn(attempt, port, rank, nprocs)
    return fn(attempt, port, rank)


def free_port(avoid: Collection[int] = ()) -> int:
    """An OS-assigned free port, never one in ``avoid``.  Sockets for
    avoided ports are held open until a fresh port is found, so the OS
    cannot hand the same one straight back (fast successive elastic
    attempts otherwise race exactly that way)."""
    held: List[socket.socket] = []
    try:
        port = 0
        for _ in range(16):
            s = socket.socket()
            held.append(s)
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
            if port not in avoid:
                break
        return port
    finally:
        for s in held:
            s.close()


def backoff_schedule(n: int, base_s: float = 0.5, max_s: float = 30.0,
                     jitter: float = 0.5, seed: int = 0) -> List[float]:
    """Delays (seconds) before restarts 1..n: exponential growth capped
    at ``max_s``, times a seeded jitter factor in ``[1, 1+jitter)``.
    Seeded => deterministic in tests, still decorrelated across
    differently-seeded supervisors stampeding a shared resource."""
    rng = random.Random(seed)
    return [min(max_s, base_s * (2.0 ** i)) * (1.0 + jitter * rng.random())
            for i in range(n)]


@dataclasses.dataclass
class AttemptResult:
    port: int
    returncodes: List[Optional[int]]
    failed_rank: Optional[int]  # first rank observed dead/nonzero
    timed_out: bool
    elapsed_s: float
    tails: Dict[int, str]       # rank -> tail of combined stdout+stderr log
    # transient OSError from Popen while spawning (ADVICE r5): recorded
    # so the failure consumes a restart instead of aborting supervision
    spawn_error: Optional[str] = None
    #: ``ok`` | ``crash`` | ``hung`` | ``spawn`` | ``timeout``
    cause: str = "crash"
    #: last heartbeat step per rank (straggler stats; empty when no rank
    #: ever beat)
    rank_steps: Dict[int, int] = dataclasses.field(default_factory=dict)
    #: backoff slept after this attempt, before the next one
    backoff_s: float = 0.0
    #: world size this attempt ran with (the degrade-and-continue policy
    #: may shrink it below the launch size — see run_elastic
    #: min_processes)
    num_processes: int = 0


@dataclasses.dataclass
class ElasticReport:
    success: bool
    attempts: List[AttemptResult]
    #: attempt 0 was an instant all-rank crash; restarts were skipped
    fail_fast: bool = False

    @property
    def restarts(self) -> int:
        return max(0, len(self.attempts) - 1)


# substrings identifying a coordinator bind race in a worker tail; the
# retry with a fresh (and different — see free_port(avoid)) port is
# exactly what a restart does, so classify as spawn-class transient
_ADDR_IN_USE = ("address already in use", "eaddrinuse")


def _classify(spawn_error: Optional[str], hung: bool, timed_out: bool,
              failed_rank: Optional[int], tails: Dict[int, str]) -> str:
    if spawn_error is not None:
        return "spawn"
    if hung:
        return "hung"
    if timed_out:
        return "timeout"
    if failed_rank is None:
        return "ok"
    # the coordinator lives in rank 0, but the bind error can surface in
    # any rank's jax.distributed bring-up — check the failed rank + rank 0
    for r in {0, failed_rank}:
        if any(pat in tails.get(r, "").lower() for pat in _ADDR_IN_USE):
            return "spawn"
    return "crash"


def run_elastic(worker_argv: Callable[[int, int, int], Sequence[str]],
                num_processes: int,
                max_restarts: int = 2,
                attempt_timeout_s: float = 600.0,
                poll_interval_s: float = 0.5,
                env: Optional[Dict[str, str]] = None,
                grace_kill_s: float = 5.0,
                per_rank_env: Optional[
                    Callable[[int, int, int], Mapping[str, str]]] = None,
                hang_timeout_s: Optional[float] = None,
                heartbeat_root: Optional[str] = None,
                backoff_base_s: float = 0.5,
                backoff_max_s: float = 30.0,
                backoff_jitter: float = 0.5,
                backoff_seed: int = 0,
                fail_fast_window_s: float = 2.0,
                min_processes: Optional[int] = None,
                degrade_after: int = 2) -> ElasticReport:
    """Supervise ``num_processes`` workers; restart the whole group on
    any failure, at most ``max_restarts`` times.

    ``worker_argv(attempt, port, rank)`` builds each worker's argv; the
    coordinator port is fresh per attempt (and never the immediately
    preceding attempt's).  ``env`` extends (not replaces) os.environ for
    every rank; ``per_rank_env(attempt, port, rank)`` adds rank-specific
    variables on top (e.g. JAX_PROCESS_ID for script workers).  The
    launcher additionally exports ``FF_ELASTIC_ATTEMPT`` (so
    failure-injection — flexflow_tpu/faults.py — can target one attempt)
    and a per-attempt ``FF_HEARTBEAT_DIR``.

    ``hang_timeout_s`` enables early hang detection: once any rank has
    heartbeat, an interval of that length in which no rank's step
    advances kills the attempt with cause ``hung`` (vs waiting out
    ``attempt_timeout_s``).  Between failed attempts the supervisor
    sleeps per :func:`backoff_schedule`; an instant all-rank nonzero
    exit on attempt 0 (within ``fail_fast_window_s``, cause ``crash``)
    aborts supervision immediately with ``fail_fast=True``.

    **Degrade-and-continue** (``min_processes``): a production machine
    that lost a rank does not get it back by retrying the dead topology
    — after ``degrade_after`` consecutive topology-class failures
    (``crash``/``hung``/``timeout``; spawn-class transients never
    count), the group size is HALVED (not below ``min_processes``) and
    supervision continues on the surviving mesh.  The shrunken world
    size is passed to ``worker_argv``/``per_rank_env`` as an optional
    4th argument (3-arg callables keep the fixed-size contract) and
    exported as ``FF_ELASTIC_NPROCS``; workers resume from the newest
    valid checkpoint and reshard onto their new mesh
    (reshard-on-resume, docs/elastic.md "Resharding").  Each
    ``AttemptResult.num_processes`` records the size its attempt ran
    with, and every shrink emits a structured ``degrade`` event.

    Returns an :class:`ElasticReport`; ``success`` means some attempt
    had every worker exit 0."""
    attempts: List[AttemptResult] = []
    # install the flight-recorder taps up front so the FIRST attempt
    # failure's dump already holds the supervisor's event trail
    from ..obs.flight import get_flight
    get_flight()
    hb_root = heartbeat_root or tempfile.mkdtemp(prefix="ff_hb_")
    backoffs = backoff_schedule(max_restarts, backoff_base_s,
                                backoff_max_s, backoff_jitter, backoff_seed)
    prev_port: Optional[int] = None
    nproc_cur = int(num_processes)
    if min_processes is not None and not 1 <= min_processes <= num_processes:
        raise ValueError(
            f"min_processes={min_processes} must be in "
            f"[1, num_processes={num_processes}]")
    topo_fails = 0  # consecutive crash/hung/timeout at the current size
    for attempt in range(max_restarts + 1):
        port = free_port(avoid=() if prev_port is None else (prev_port,))
        prev_port = port
        hb_dir = os.path.join(hb_root, f"attempt{attempt}")
        os.makedirs(hb_dir, exist_ok=True)
        worker_env = dict(os.environ)
        if env:
            worker_env.update(env)
        worker_env["FF_ELASTIC_ATTEMPT"] = str(attempt)
        worker_env["FF_HEARTBEAT_DIR"] = hb_dir
        worker_env["FF_ELASTIC_NPROCS"] = str(nproc_cur)
        procs: List[subprocess.Popen] = []
        # per-rank log FILES, not pipes: an undrained pipe blocks the
        # worker after ~64 KB of output (a verbose XLA warning dump
        # would masquerade as a hang and burn an attempt)
        logdir = tempfile.mkdtemp(prefix=f"ff_elastic_a{attempt}_")
        logs = []
        t0 = time.monotonic()
        failed_rank: Optional[int] = None
        timed_out = False
        hung = False
        spawn_error: Optional[str] = None
        last_hb: Dict[int, int] = {}
        last_progress = t0
        try:
            # a transient OSError (fd exhaustion, ENOMEM, a briefly
            # missing interpreter on shared storage) from open/Popen is
            # an attempt FAILURE, not a supervision abort: record it,
            # reap whatever spawned, and let the restart loop retry
            try:
                if spawn_fail_requested(worker_env, attempt):
                    raise OSError(
                        f"injected spawn_fail_attempt:{attempt} (FF_FAULT)")
                for rank in range(nproc_cur):
                    lf = open(os.path.join(logdir, f"rank{rank}.log"),
                              "w+b")
                    logs.append(lf)
                    env_r = worker_env
                    if per_rank_env is not None:
                        env_r = dict(worker_env)
                        env_r.update(_call_sized(per_rank_env, attempt,
                                                 port, rank, nproc_cur))
                    procs.append(subprocess.Popen(
                        list(_call_sized(worker_argv, attempt, port,
                                         rank, nproc_cur)),
                        stdout=lf, stderr=subprocess.STDOUT,
                        env=env_r))
            except OSError as e:
                failed_rank = len(procs)  # the rank that failed to spawn
                spawn_error = f"{type(e).__name__}: {e}"
            while spawn_error is None:
                codes = [p.poll() for p in procs]
                bad = [r for r, c in enumerate(codes)
                       if c is not None and c != 0]
                if bad:
                    failed_rank = bad[0]
                    break
                if all(c == 0 for c in codes):
                    break
                now = time.monotonic()
                if now - t0 > attempt_timeout_s:
                    timed_out = True
                    break
                if hang_timeout_s is not None:  # no monitor, no disk I/O
                    hb = read_heartbeats(hb_dir)
                    if hb != last_hb:    # a new rank appeared or a step
                        last_hb = hb     # advanced: that is progress
                        last_progress = now
                    elif hb and now - last_progress > hang_timeout_s:
                        hung = True
                        break
                time.sleep(poll_interval_s)
            if (attempt == 0 and failed_rank is not None
                    and spawn_error is None
                    and time.monotonic() - t0 <= fail_fast_window_s):
                # possible config-error signature: give the remaining
                # ranks the rest of the window to exit ON THEIR OWN —
                # only an all-rank self-exit counts (a rank we kill
                # below would be indistinguishable from a crasher)
                while (any(p.poll() is None for p in procs)
                        and time.monotonic() - t0 <= fail_fast_window_s):
                    time.sleep(0.05)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            deadline = time.monotonic() + grace_kill_s
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        tails = {}
        for r, lf in enumerate(logs):
            try:
                lf.flush()
                lf.seek(0, os.SEEK_END)
                size = lf.tell()
                lf.seek(max(0, size - 800))
                tails[r] = lf.read().decode("utf-8", "replace")
            except Exception:
                tails[r] = "<log unavailable>"
            finally:
                lf.close()
        cause = _classify(spawn_error, hung, timed_out, failed_rank, tails)
        result = AttemptResult(
            port=port,
            returncodes=[p.returncode for p in procs],
            failed_rank=failed_rank,
            timed_out=timed_out or hung,
            elapsed_s=round(time.monotonic() - t0, 3), tails=tails,
            spawn_error=spawn_error, cause=cause,
            rank_steps=read_heartbeats(hb_dir),
            num_processes=nproc_cur)
        attempts.append(result)
        if cause == "ok" and all(c == 0 for c in result.returncodes):
            return ElasticReport(True, attempts)
        # supervisor attempt failure: a flight-recorder trigger (no-op
        # unless FF_FLIGHT_DIR is set) — the dump retains the recent
        # degrade/checkpoint_skipped/heartbeat event trail plus this
        # attempt's classification and per-rank tails
        from ..obs.flight import flight_dump
        flight_dump("elastic_attempt_failed", extra={
            "attempt": attempt, "cause": cause,
            "num_processes": nproc_cur,
            "returncodes": result.returncodes,
            "failed_rank": failed_rank,
            "tail": (result.tails.get(failed_rank, "")[-400:]
                     if failed_rank is not None else "")})
        if (attempt == 0 and cause == "crash"
                and result.elapsed_s <= fail_fast_window_s
                and result.returncodes
                and all(c not in (0, None) and c >= 0
                        for c in result.returncodes)):
            # every rank self-exited nonzero near-instantly (negative
            # codes are our own kills, excluded): argv/config error —
            # retrying max_restarts times would yield the same failure
            return ElasticReport(False, attempts, fail_fast=True)
        # degrade-and-continue: repeated topology-class failures mean
        # the machine shrank under us — stop retrying the dead world
        # size, resume on the surviving mesh (spawn-class transients
        # neither count nor reset the streak)
        if cause in ("crash", "hung", "timeout"):
            topo_fails += 1
        if (min_processes is not None and nproc_cur > min_processes
                and topo_fails >= max(1, int(degrade_after))):
            # nproc_cur > min_processes >= 1 guarantees the halving
            # (floored at the min) strictly shrinks the world
            new_size = max(int(min_processes), nproc_cur // 2)
            from ..fflogger import get_logger
            get_logger("elastic").event(
                "degrade", attempt=attempt, cause=cause,
                from_processes=nproc_cur, to_processes=new_size,
                consecutive_failures=topo_fails)
            nproc_cur = new_size
            topo_fails = 0
        if attempt < max_restarts and backoffs[attempt] > 0:
            result.backoff_s = round(backoffs[attempt], 3)
            time.sleep(backoffs[attempt])
    return ElasticReport(False, attempts)


def latest_checkpoint(directory: str, prefix: str = "elastic") -> Optional[str]:
    """Newest ``<prefix>_step*.npz`` checkpoint in ``directory``, or
    None.  Sorted by the step number embedded in the name, not mtime —
    ranks may observe different mtimes on shared storage.  Trusts the
    file blindly; the elastic resume path should prefer
    :func:`latest_valid_checkpoint`."""
    found = _step_checkpoints(directory, prefix)
    return found[0][1] if found else None


def latest_valid_checkpoint(directory: str,
                            prefix: str = "elastic") -> Optional[str]:
    """Newest checkpoint in ``directory`` that passes verification
    (full read + manifest CRCs, the ``resilience.verify_checkpoint``
    predicate), falling back step by step past corrupt/truncated files.
    A bit-rotted newest checkpoint on shared storage therefore costs
    one save interval instead of wedging every restart attempt in a
    resume-crash loop — and every skipped file is surfaced as a
    structured ``checkpoint_skipped`` event naming the path and WHY
    (an operator staring at a job that silently lost a save interval
    deserves better than silence).  Shares the one scan implementation
    with the worker-side ``resilience.elastic_resume``."""
    from ..resilience import iter_valid_checkpoints
    for _, path, _data in iter_valid_checkpoints(directory, prefix):
        return path
    return None


def _step_checkpoints(directory: str, prefix: str):
    """``(step, path)`` for every ``<prefix>_step<N>.npz``, newest first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for n in names:
        if not (n.startswith(prefix + "_step") and n.endswith(".npz")):
            continue
        if n.endswith(".tmp.npz"):
            continue  # unpublished partial write, never a resume source
        try:
            step = int(n[len(prefix + "_step"):-len(".npz")])
        except ValueError:
            continue
        found.append((step, os.path.join(directory, n)))
    found.sort(key=lambda sp: sp[0], reverse=True)
    return found
