"""Pipeline parallelism — GPipe-style collective pipeline over the ``p``
mesh axis.

The reference has NO stage-based pipeline (SURVEY §2.15: per-op
``device_ids`` + Legion async task issue give only *implicit* overlap; the
NMT engine chunks timesteps the same way).  This module goes beyond it with
an explicit TPU-native pipeline: homogeneous stages hold their stacked
weights sharded over ``p`` (one stage per p-rank), microbatches stream
through a ``lax.scan`` of ticks, activations hop stage-to-stage with
``lax.ppermute``, and the final stage's emissions are psum-gathered.
Gradients fall out of autodiff through the scan (ppermute and psum are
linear), giving synchronous GPipe semantics: all microbatch gradients
accumulate before the update — no staleness.

Schedules:

* ``"gpipe"`` (default): tick t runs stage s on microbatch ``t - s``; a
  rank holding v stacked stages runs its whole group per tick, so a step
  costs ``(S + M - 1) * v`` stage-times — bubble fraction (S-1)/(S+M-1).
* ``"interleaved"`` (Megatron-style virtual stages): each rank holds v
  round-robin chunks (global stage t lives on rank ``t % S``) and runs ONE
  stage per tick; activations carry a (chunk, microbatch) tag around a
  ppermute ring with wraparound, and rank 0 injects a fresh microbatch
  whenever the wrap slot is empty.  The tick count is computed exactly by
  a static dataflow simulation — ~``v*M + S + v`` stage-times, cutting the
  bubble by ~v versus gpipe.  Traversal order is round-robin by
  construction; the p==1 fallback applies stages in the same order so
  numerics match the pipelined run exactly.

Gradients for both schedules come from autodiff through the scan
(ppermute/psum/dynamic_index are linear; their transposes reverse the
schedule), so there is no hand-written backward.

Why no 1F1B (VERDICT r3 #6 "consider 1F1B"): 1F1B's advantage over GPipe
is peak-activation memory — it caps in-flight microbatches at S by
running each microbatch's backward as soon as its forward clears the
last stage, which requires hand-interleaving fwd and bwd ticks in one
schedule and therefore a hand-written backward (autodiff cannot reverse
an interleaved schedule; the transpose of a scan is a scan in strict
reverse order).  Under XLA the same memory cap is reached compositionally:
``cfg.remat`` wraps stage forwards in ``jax.checkpoint`` (activations of
non-live microbatches are recomputed, not stored) and the interleaved
schedule already shrinks the bubble ~v-fold, while keeping gradients
autodiff-derived (provably consistent with the p==1 fallback — the
parity tests pin this).  Hand-scheduling 1F1B would trade that proof and
XLA's fusion freedom for memory we can already trade with remat.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from ..compat import shard_map, shard_map_partial_auto_supported
from .mesh import MachineMesh


def traversal_order(total_stages: int, S: int, schedule: str):
    """Storage-index visit order of the pipeline.  gpipe visits the stage
    dim in storage order; interleaved visits round-robin over ranks
    (traversal step t -> storage index (t % S) * v + t // S, i.e. rank
    t % S, local chunk t // S under contiguous p-sharding)."""
    if schedule != "interleaved" or S <= 1:
        return list(range(total_stages))
    v = total_stages // S
    return [(t % S) * v + t // S for t in range(total_stages)]


def _interleaved_ticks(S: int, M: int, v: int) -> int:
    """Exact tick count of the interleaved dataflow (static Python
    simulation of the tag protocol — the same priority rule the traced
    tick uses: an arriving wrapped unit beats a pending injection)."""
    arriving = [None] * S  # unit at each rank's input: (mb, chunk)
    inj = done = t = 0
    while done < M:
        nxt = [None] * S
        for r in range(S):
            unit = arriving[r]
            if r == 0 and unit is None and inj < M:
                unit = (inj, 0)
                inj += 1
            if unit is None:
                continue
            mb, c = unit
            if r == S - 1:
                if c == v - 1:
                    done += 1  # final stage of final chunk -> output
                else:
                    nxt[0] = (mb, c + 1)  # wrap to rank 0, next chunk
            else:
                nxt[r + 1] = (mb, c)
        arriving = nxt
        t += 1
    return t


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: MachineMesh,
                   num_microbatches: Optional[int] = None,
                   schedule: str = "gpipe",
                   virtual_stages: Optional[int] = None):
    """Run the stacked stages over ``x`` as a collective pipeline.
    Returns ``(y, aux)`` — aux is the per-batch sum of the stages'
    auxiliary losses (0 when stage_fn returns a bare array).

    stage_fn(params, x) -> y [or (y, aux_scalar)] with y.shape == x.shape
    (shape-homogeneous stages; the stage BODY is arbitrary — see
    ops/pipeline.PipelineSegment for stages built from any FFModel
    subgraph, including MoE);
    ``stacked_params``: pytree whose leaves carry a leading stage dim,
    sharded over the mesh's ``p`` axis.  x: (n, ...) activations; returns
    same-shaped y.  ``schedule``: "gpipe" or "interleaved"; the latter
    REQUIRES ``virtual_stages`` (chunks per rank), which pins the
    traversal order mesh-independently — the p==1 fallback then
    reproduces the pipelined numerics exactly.

    Only the ``p`` sub-axes are MANUAL in the shard_map — every other
    mesh axis stays auto, so activations keep their ``n`` (data) sharding
    and stage bodies may carry ``c`` (tensor) and ``e`` (expert) sharding
    constraints inside: GSPMD inserts the TP/MoE collectives within each
    pipeline rank.  This is what composes {n, c, e, p} in one program.
    """
    assert schedule in ("gpipe", "interleaved"), schedule
    leaves = jax.tree.leaves(stacked_params)
    total_stages = leaves[0].shape[0]
    for leaf in leaves:
        assert leaf.shape[0] == total_stages, \
            "all stacked leaves must share the stage dim"

    def sfn(params, h):  # normalize: stages may or may not emit aux
        r = stage_fn(params, h)
        return r if isinstance(r, tuple) else (r, jnp.float32(0.0))

    if schedule == "interleaved":
        if not virtual_stages or total_stages % virtual_stages != 0:
            raise ValueError(
                f"interleaved schedule needs virtual_stages dividing "
                f"num_stages={total_stages}, got {virtual_stages}")
        S_eff = total_stages // virtual_stages  # required pipeline width
    S = mesh.axis_size("p")
    # a partial-auto shard_map (p manual, other mesh axes live — n data
    # sharding handled by GSPMD) only compiles on the modern surface;
    # the legacy one (compat) rejects/aborts it, so take the SAME-MATH
    # sequential fallback there — parity with the pipelined schedule is
    # exact by construction (the p==1 path below), only the bubble
    # overlap is lost on that jax version
    legacy_partial = (
        S > 1 and not shard_map_partial_auto_supported()
        and any(mesh.mesh.shape[a] > 1 for a in mesh.mesh.axis_names
                if a not in mesh.subaxes("p")))
    if S <= 1 or legacy_partial:
        # sequential fallback: same math in the schedule's traversal order
        order = traversal_order(total_stages,
                                S_eff if schedule == "interleaved" else 1,
                                schedule)
        ordered = jax.tree.map(lambda a: a[jnp.asarray(order)],
                               stacked_params) if order != list(
            range(total_stages)) else stacked_params

        def body(h, params):
            y, aux = sfn(params, h)
            return y, aux

        y, auxs = lax.scan(body, x, ordered)
        return y, jnp.sum(auxs)

    if total_stages % S != 0:
        raise ValueError(
            f"num_stages={total_stages} must be a multiple of the mesh 'p' "
            f"axis size {S} (each rank runs a group of stages)")
    if schedule == "interleaved" and S != S_eff:
        raise ValueError(
            f"interleaved schedule with virtual_stages={virtual_stages} "
            f"needs mesh p == {S_eff}, got {S}")
    M = num_microbatches or S
    p_axes = mesh.subaxes("p")
    # activations enter with their data (n) sharding intact on the AUTO
    # axes; only the stage dim of the weights is a manual (p) spec
    x_spec = PartitionSpec(*([None] * x.ndim))
    pspec = jax.tree.map(
        lambda a: PartitionSpec(p_axes, *([None] * (a.ndim - 1))),
        stacked_params)

    if schedule == "interleaved":
        v = virtual_stages
        fn = partial(_pipeline_interleaved_local, stage_fn=sfn, S=S,
                     M=M, v=v, p_axes=p_axes,
                     ticks=_interleaved_ticks(S, M, v))
    else:
        fn = partial(_pipeline_local, stage_fn=sfn, S=S, M=M,
                     p_axes=p_axes)
    # rank identity rides in as a p-sharded operand instead of
    # lax.axis_index: under the legacy partial-auto shard_map surface
    # (compat) axis_index lowers to a PartitionId instruction XLA's
    # SPMD partitioner rejects when auto axes are present; an explicit
    # arange sharded over p gives every rank the same value portably
    rank_ids = jnp.arange(S, dtype=jnp.int32)
    # the aux accumulator crosses the shard_map boundary as shape (1,),
    # not a scalar: a 0-d value carried through the inner lax.scan
    # breaks the LEGACY shard_map's autodiff (its partial-eval gives
    # the scalar residual a dim-0 spec and raises _SpecError on the
    # grad path — minimal repro pinned while migrating to compat)
    y, aux = shard_map(
        fn, mesh.mesh,
        in_specs=(pspec, x_spec, PartitionSpec(p_axes)),
        out_specs=(x_spec, PartitionSpec(None)), check_vma=False,
        axis_names=frozenset(p_axes))(stacked_params, x, rank_ids)
    return y, aux[0]


def _pipeline_interleaved_local(stacked_local, x_loc, rank_arr, *,
                                stage_fn, S: int, M: int, v: int, p_axes,
                                ticks: int):
    """Per-rank interleaved (virtual-stage) loop.  This rank holds v
    chunks; local chunk c is global stage ``c*S + rank``.  Each activation
    rides the full ring carrying (chunk, microbatch) tags; rank S-1 wraps
    non-final chunks back to rank 0, which otherwise injects fresh
    microbatches.  One stage-application per rank per tick.
    ``rank_arr`` is this rank's (1,) slice of the p-sharded arange —
    the portable axis_index (see pipeline_apply)."""
    idx = rank_arr[0]
    n_loc = x_loc.shape[0]
    assert n_loc % M == 0, (n_loc, M)
    xm = x_loc.reshape((M, n_loc // M) + x_loc.shape[1:])
    ring = [(j, (j + 1) % S) for j in range(S)]

    x0 = jnp.zeros_like(xm[0])
    tag0 = jnp.asarray(-1, jnp.int32)   # chunk of the arriving unit; -1=idle
    mb0 = jnp.asarray(0, jnp.int32)
    inj0 = jnp.asarray(0, jnp.int32)    # next microbatch to inject (rank 0)
    out0 = jnp.zeros_like(xm)
    # (1,)-shaped, never 0-d: see pipeline_apply's out_specs note
    aux0 = jnp.zeros((1,), jnp.float32)

    def tick(carry, _):
        x_arr, tag, mb, inj, out, aux = carry
        can_inject = (idx == 0) & (tag < 0) & (inj < M)
        x_in = jnp.where(can_inject, xm[jnp.clip(inj, 0, M - 1)], x_arr)
        tag = jnp.where(can_inject, 0, tag)
        mb = jnp.where(can_inject, inj, mb)
        inj = inj + can_inject.astype(inj.dtype)
        chunk_params = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(
                a, jnp.clip(tag, 0, v - 1), 0, keepdims=False),
            stacked_local)
        y, a = stage_fn(chunk_params, x_in)
        y = y.astype(x_in.dtype)
        y = jnp.where(tag >= 0, y, x_in)    # idle tick: pass-through mask
        aux = aux + jnp.where(tag >= 0, a, 0.0)  # idle ticks chew garbage
        is_final = (idx == S - 1) & (tag == v - 1)
        emitted = out.at[jnp.clip(mb, 0, M - 1)].set(y)
        out = jnp.where(is_final & (tag >= 0), emitted, out)
        # chunk advances on the wrap past the last rank; final chunks leave
        # the ring as an empty slot rank 0 can fill
        send_tag = jnp.where(
            tag < 0, -1,
            jnp.where(idx == S - 1,
                      jnp.where(tag == v - 1, -1, tag + 1), tag))
        x_nxt = lax.ppermute(y, p_axes, ring)
        tag_nxt = lax.ppermute(send_tag, p_axes, ring)
        mb_nxt = lax.ppermute(mb, p_axes, ring)
        return (x_nxt, tag_nxt, mb_nxt, inj, out, aux), None

    (_, _, _, _, out, aux), _ = lax.scan(
        tick, (x0, tag0, mb0, inj0, out0, aux0), jnp.arange(ticks))
    out = lax.psum(jnp.where(idx == S - 1, out, jnp.zeros_like(out)), p_axes)
    # /M rescales the M per-microbatch aux terms to the p==1 fallback's
    # full-batch scale.  EXACT only for batch-linear aux (plain means);
    # nonlinear statistics like MoE's sum_e f_e*P_e load-balance loss
    # differ from the full-batch value by O(microbatch variance) — parity
    # tests against p==1 need a tolerance, not exactness.
    aux = lax.psum(aux, p_axes) / M
    return out.reshape(x_loc.shape), aux


def _pipeline_local(stacked_local, x_loc, rank_arr, *, stage_fn, S: int,
                    M: int, p_axes):
    """Per-device GPipe loop (runs inside shard_map).  Each rank holds a
    contiguous GROUP of stages (total_stages / S per rank, often 1) and
    applies them in order within its tick.  ``rank_arr`` is this rank's
    (1,) slice of the p-sharded arange (portable axis_index)."""
    idx = rank_arr[0]
    n_loc = x_loc.shape[0]
    assert n_loc % M == 0, (n_loc, M)
    xm = x_loc.reshape((M, n_loc // M) + x_loc.shape[1:])
    state0 = jnp.zeros_like(xm[0])
    out0 = jnp.zeros_like(xm)
    # activations hop s -> s+1; rank 0 has no upstream (it injects)
    perm = [(j, j + 1) for j in range(S - 1)]

    def run_group(x_in):
        # scan this rank's local stage group in order
        def body(h, params):
            y, a = stage_fn(params, h)
            return y.astype(h.dtype), a

        y, auxs = lax.scan(body, x_in, stacked_local)
        return y, jnp.sum(auxs)

    def tick(carry, t):
        state, out, aux = carry
        mb_in = xm[jnp.clip(t, 0, M - 1)]
        x_in = jnp.where(idx == 0, mb_in, state)
        y, a = run_group(x_in)
        y = y.astype(state.dtype)
        # this rank computes real data only at ticks idx <= t < idx + M;
        # bubble ticks chew zeros whose aux must not count
        aux = aux + jnp.where((t >= idx) & (t < idx + M), a, 0.0)
        m = t - (S - 1)  # microbatch the LAST stage just finished
        emitted = out.at[jnp.clip(m, 0, M - 1)].set(y)
        valid = (idx == S - 1) & (m >= 0)
        out = jnp.where(valid, emitted, out)
        state = lax.ppermute(y, p_axes, perm)
        return (state, out, aux), None

    # (1,)-shaped aux carry, never 0-d: see pipeline_apply's note
    (state, out, aux), _ = lax.scan(
        tick, (state0, out0, jnp.zeros((1,), jnp.float32)),
        jnp.arange(S + M - 1))
    # only the last rank holds real outputs; broadcast around the ring
    out = lax.psum(jnp.where(idx == S - 1, out, jnp.zeros_like(out)), p_axes)
    # /M rescales per-microbatch aux to full-batch scale (exact only for
    # batch-linear aux — see the interleaved loop's note)
    aux = lax.psum(aux, p_axes) / M
    return out.reshape(x_loc.shape), aux
