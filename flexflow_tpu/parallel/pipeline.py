"""Pipeline parallelism — GPipe-style collective pipeline over the ``p``
mesh axis.

The reference has NO stage-based pipeline (SURVEY §2.15: per-op
``device_ids`` + Legion async task issue give only *implicit* overlap; the
NMT engine chunks timesteps the same way).  This module goes beyond it with
an explicit TPU-native pipeline: homogeneous stages hold their stacked
weights sharded over ``p`` (one stage per p-rank), microbatches stream
through a ``lax.scan`` of ticks, activations hop stage-to-stage with
``lax.ppermute``, and the final stage's emissions are psum-gathered.
Gradients fall out of autodiff through the scan (ppermute and psum are
linear), giving synchronous GPipe semantics: all microbatch gradients
accumulate before the update — no staleness.

Schedule: tick t runs stage s on microbatch ``t - s`` (valid range only),
so a step costs S + M - 1 ticks for S stages x M microbatches — the classic
bubble fraction (S-1)/(S+M-1); raise ``num_microbatches`` to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from .mesh import MachineMesh


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh: MachineMesh,
                   num_microbatches: Optional[int] = None):
    """Run ``y = stage_{S-1}(... stage_0(x))`` as a collective pipeline.

    stage_fn(params, x) -> y with y.shape == x.shape (homogeneous stages);
    ``stacked_params``: pytree whose leaves carry a leading stage dim S,
    sharded over the mesh's ``p`` axis.  x: (n, ...) activations (may be
    sharded over ``n``); returns same-shaped y.
    """
    leaves = jax.tree.leaves(stacked_params)
    total_stages = leaves[0].shape[0]
    for leaf in leaves:
        assert leaf.shape[0] == total_stages, \
            "all stacked leaves must share the stage dim"
    S = mesh.axis_size("p")
    if S <= 1:
        # sequential fallback: same math, one stage after another
        def body(h, params):
            return stage_fn(params, h), None

        y, _ = lax.scan(body, x, stacked_params)
        return y

    if total_stages % S != 0:
        raise ValueError(
            f"num_stages={total_stages} must be a multiple of the mesh 'p' "
            f"axis size {S} (each rank runs a contiguous group of stages)")
    M = num_microbatches or S
    p_axes = mesh.subaxes("p")
    n_axes = mesh.subaxes("n")
    n_sharded = bool(n_axes) and x.shape[0] % (mesh.axis_size("n") * M) == 0
    x_spec = PartitionSpec(n_axes if n_sharded else None,
                           *([None] * (x.ndim - 1)))
    pspec = jax.tree.map(
        lambda a: PartitionSpec(p_axes, *([None] * (a.ndim - 1))),
        stacked_params)

    fn = partial(_pipeline_local, stage_fn=stage_fn, S=S, M=M, p_axes=p_axes)
    return jax.shard_map(fn, mesh=mesh.mesh, in_specs=(pspec, x_spec),
                         out_specs=x_spec, check_vma=False)(stacked_params, x)


def _pipeline_local(stacked_local, x_loc, *, stage_fn, S: int, M: int,
                    p_axes):
    """Per-device GPipe loop (runs inside shard_map).  Each rank holds a
    contiguous GROUP of stages (total_stages / S per rank, often 1) and
    applies them in order within its tick."""
    idx = lax.axis_index(p_axes)
    n_loc = x_loc.shape[0]
    assert n_loc % M == 0, (n_loc, M)
    xm = x_loc.reshape((M, n_loc // M) + x_loc.shape[1:])
    state0 = jnp.zeros_like(xm[0])
    out0 = jnp.zeros_like(xm)
    # activations hop s -> s+1; rank 0 has no upstream (it injects)
    perm = [(j, j + 1) for j in range(S - 1)]

    def run_group(x_in):
        # scan this rank's local stage group in order
        def body(h, params):
            return stage_fn(params, h).astype(h.dtype), None

        y, _ = lax.scan(body, x_in, stacked_local)
        return y

    def tick(carry, t):
        state, out = carry
        mb_in = xm[jnp.clip(t, 0, M - 1)]
        x_in = jnp.where(idx == 0, mb_in, state)
        y = run_group(x_in).astype(state.dtype)
        m = t - (S - 1)  # microbatch the LAST stage just finished
        emitted = out.at[jnp.clip(m, 0, M - 1)].set(y)
        valid = (idx == S - 1) & (m >= 0)
        out = jnp.where(valid, emitted, out)
        state = lax.ppermute(y, p_axes, perm)
        return (state, out), None

    (state, out), _ = lax.scan(tick, (state0, out0), jnp.arange(S + M - 1))
    # only the last rank holds real outputs; broadcast around the ring
    out = lax.psum(jnp.where(idx == S - 1, out, jnp.zeros_like(out)), p_axes)
    return out.reshape(x_loc.shape)
