"""Shared persistent XLA compile-cache setup.

Chip windows on the tunnel rig are scarce and a cold model compile
costs minutes of window; the persistent cache makes every compile after
the first warm — across bench.py runs, the chip-queue scripts, the test
suite (tests/subproc.CACHE_DIR points at the same directory), and the
driver's end-of-round sweep.  Cache keys include backend and topology,
so CPU-mesh test entries and single-chip TPU entries coexist safely.

Called explicitly by harnesses (bench.py, scripts/*) rather than on
library import so embedding applications keep control of their own
jax.config.

Multi-model processes (a serving fleet — serving/fleet): XLA keys
entries on the lowered HLO + backend/topology, so two tenants with
IDENTICAL graphs/shapes share one on-disk entry — which is correct
and desirable (the executable is parameter-free; params are call
arguments).  Per-model separation of the IN-PROCESS bucket
executables is the job of ``FFModel.forward_compiled``'s
``(bucket, exec_digest)`` key, not this cache: model B can never be
handed an executable lowered for model A's graph/strategies/mesh
even when both warmed the same persistent cache
(tests/test_fleet.py pins the collision case).
"""

from __future__ import annotations

import os


def default_dir() -> str:
    """The chip-surface cache directory (repo-level ``.jax_cache_chip``)
    — the ONE spelling shared by enable(), bench.py's abort-recovery
    clear, and the tests."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache_chip")


def _resolve_dir(cache_dir: str | None) -> tuple[str, bool]:
    """(directory, explicit) for an ``enable()`` call: an argument or an
    ``FF_CACHE_DIR`` env override is EXPLICIT (the operator picked the
    surface); the built-in default is not, and must never displace a
    cache dir some other harness already configured (e.g. the test
    suite's session-scoped ``.jax_cache`` — mixing surfaces can abort
    the reader, see below)."""
    if cache_dir is not None:
        return cache_dir, True
    env = os.environ.get("FF_CACHE_DIR")
    if env:
        return env, True
    return default_dir(), False


def enable(cache_dir: str | None = None) -> None:
    """Point jax at the repo-level ``.jax_cache_chip`` (or
    ``cache_dir``, or the ``FF_CACHE_DIR`` env override).
    ``FF_BENCH_NO_CACHE=1`` opts out (A/B hygiene when timing
    compiles).  Never raises: the cache is an optimization.

    Idempotent: repeated calls with the same resolved directory do not
    churn jax.config, and a DEFAULT call (no argument, no env) defers
    to any cache dir already configured — the serving engine calls
    ``enable()`` unconditionally at startup, which must be a no-op
    under harnesses (tests/conftest.py, bench.py) that already picked
    their surface.

    Deliberately a DIFFERENT directory from the test suite's
    ``.jax_cache`` (tests/subproc.CACHE_DIR): chip-side processes (axon
    backend) also emit XLA:CPU entries for host-side glue whose machine
    feature strings differ from the CPU-mesh suite's, and loading a
    foreign-featured AOT entry can SIGILL/abort the reader (observed:
    cpu_aot_loader 'machine type ... doesn't match' followed by a fatal
    abort in the suite).  One surface, one cache."""
    if os.environ.get("FF_BENCH_NO_CACHE"):
        return
    cache_dir, explicit = _resolve_dir(cache_dir)
    try:
        import jax

        current = jax.config.jax_compilation_cache_dir
        if current == cache_dir:
            return  # already on this surface; don't churn jax.config
        if current and not explicit:
            return  # a harness already picked a surface; keep it
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache only compiles that cost real time: the tiny-jit entries
        # (bernoulli, broadcast, ...) are cheap to redo but multiply the
        # on-disk write volume ~10x, and every write is a chance for a
        # killed process (timeouts are routine on this rig) to leave a
        # stale/truncated entry behind.  Cross-session reuse is safe
        # HERE because chip programs are single-device (no collectives)
        # — multi-device CPU executables deserialized from stale entries
        # can deadlock their collective rendezvous and abort (see
        # tests/conftest.py, which session-scopes the TEST cache for
        # exactly that reason); bench's sweep additionally clears this
        # dir and retries once if a child aborts.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass
