"""Shared persistent XLA compile-cache setup.

Chip windows on the tunnel rig are scarce and a cold model compile
costs minutes of window; the persistent cache makes every compile after
the first warm — across bench.py runs, the chip-queue scripts, the test
suite (tests/subproc.CACHE_DIR points at the same directory), and the
driver's end-of-round sweep.  Cache keys include backend and topology,
so CPU-mesh test entries and single-chip TPU entries coexist safely.

Called explicitly by harnesses (bench.py, scripts/*) rather than on
library import so embedding applications keep control of their own
jax.config.
"""

from __future__ import annotations

import os


def enable(cache_dir: str | None = None) -> None:
    """Point jax at the repo-level ``.jax_cache`` (or ``cache_dir``).
    ``FF_BENCH_NO_CACHE=1`` opts out (A/B hygiene when timing
    compiles).  Never raises: the cache is an optimization."""
    if os.environ.get("FF_BENCH_NO_CACHE"):
        return
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass
