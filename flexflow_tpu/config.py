"""FFConfig / ParallelConfig — run configuration and the strategy atom.

TPU-native re-design of the reference's ``include/config.h`` (FFConfig,
ParallelConfig; defaults in ``src/runtime/model.cc:1182-1219``; CLI parser
``model.cc:1221-1289``).  The reference counts CUDA GPUs per node
(``-ll:gpu``); here the worker unit is a TPU chip in a ``jax`` device mesh
(``-ll:tpu``, with ``-ll:gpu`` accepted as a compatibility alias).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Tuple

MAX_TENSOR_DIM = 4  # logical graph dims, matching reference config.h:30
MAX_SEQ_DIM = 1


class DeviceType(enum.IntEnum):
    """Mirrors strategy.proto's Op.DeviceType (GPU=0, CPU=1).

    On TPU the accelerator slot is the TPU chip; ``DEVICE`` keeps the
    wire-format value 0 so existing strategy files parse unchanged.  ``HOST``
    (=CPU) marks ops placed on the host — the reference uses this for DLRM
    embedding tables (``dlrm_strategy_hetero.cc``); we map it to host-memory
    offload.
    """

    DEVICE = 0  # accelerator (TPU chip); reference: GPU
    HOST = 1    # host CPU

    # aliases for reference-parity spelling
    GPU = 0
    CPU = 1
    TPU = 0


class MemoryType(enum.IntEnum):
    """Mirrors strategy.proto Op.MemoryType: FBM (device HBM) / ZCM (host)."""

    FBM = 0  # device framebuffer -> TPU HBM
    ZCM = 1  # zero-copy (host-pinned) -> host memory


# The per-op precision axis of the SOAP space (ISSUE 14): a strategy may
# pin one op's compute dtype independently of FFConfig.compute_dtype.
# "" = follow the run's global compute dtype (the backward-compatible
# default every shipped .pb reads as); "bf16"/"f32" force the op.  Wire
# values in strategy.proto field 6: 0 = follow, 1 = bf16, 2 = f32.
PRECISIONS = ("", "bf16", "f32")
# precision token -> jnp dtype name (the "" default resolves to the
# session dtype at the ONE trace-time resolution point, ops/common.py)
PRECISION_DTYPES = {"bf16": "bfloat16", "f32": "float32"}
# dtype names FFConfig.compute_dtype / param_dtype may take — validated
# at construction so a typo fails with the field name, not deep inside
# jnp.dtype at trace time
VALID_COMPUTE_DTYPES = ("bfloat16", "float32", "float16")
VALID_PARAM_DTYPES = ("float32", "bfloat16", "float64")


def _validate_dtype_field(field: str, value: str, allowed) -> None:
    if value not in allowed:
        raise ValueError(
            f"FFConfig.{field} must be one of {', '.join(allowed)}, got "
            f"{value!r}")


def dtype_short(dtype_name: str) -> str:
    """The ONE dtype -> bench-tag spelling ("bfloat16" -> "bf16"), so
    every bench's precision_policy stamp shares a vocabulary."""
    return {"bfloat16": "bf16", "float32": "f32",
            "float16": "f16"}.get(dtype_name, dtype_name)


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """The SOAP strategy atom (reference ``config.h:42-51``).

    ``dims[i]`` is the partition degree of logical tensor dim ``i`` of the
    op's *output* tensor, ordered outermost-first (sample dim first) —
    note the reference stores ``adim`` innermost-first; we use natural
    (row-major, sample-major) order throughout and convert at the strategy
    file boundary.

    ``device_ids`` enumerates the flat mesh coordinates owning each part
    (row-major over ``dims``).  On TPU, device ids index into the flattened
    ``jax`` device mesh rather than Legion processor lists.
    """

    device_type: DeviceType = DeviceType.DEVICE
    dims: Tuple[int, ...] = (1,)
    device_ids: Tuple[int, ...] = (0,)
    memory_types: Tuple[MemoryType, ...] = ()
    # per-op precision (the SOAP precision axis, ISSUE 14): "" follows
    # FFConfig.compute_dtype — the default every pre-existing strategy
    # (and every shipped .pb, which has no field 6) resolves to, so the
    # default policy is bit-identical to a build without the axis.
    precision: str = ""

    def __post_init__(self):
        if self.precision not in PRECISIONS:
            raise ValueError(
                f"ParallelConfig.precision must be one of "
                f"{PRECISIONS}, got {self.precision!r}")

    @property
    def num_parts(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def with_dims(self, dims: Sequence[int]) -> "ParallelConfig":
        nparts = 1
        for d in dims:
            nparts *= d
        return ParallelConfig(
            device_type=self.device_type,
            dims=tuple(int(d) for d in dims),
            device_ids=tuple(range(nparts)),
            memory_types=self.memory_types,
            precision=self.precision,
        )

    @staticmethod
    def data_parallel(num_parts: int, ndims: int = 2) -> "ParallelConfig":
        """Reference ``Op::get_data_parallel_config`` (model.cc:263-274):
        partition only the sample (outermost) dim."""
        dims = (num_parts,) + (1,) * (ndims - 1)
        return ParallelConfig(
            device_type=DeviceType.DEVICE,
            dims=dims,
            device_ids=tuple(range(num_parts)),
        )


class CompMode(enum.Enum):
    TRAINING = "training"
    INFERENCE = "inference"


@dataclasses.dataclass
class FFConfig:
    """Run configuration (reference ``config.h:66-103``).

    Reference defaults from ``model.cc:1182-1197``: epochs=1, batchSize=64,
    lr=0.01, wd=0.0001, workersPerNode=0, numNodes=1, search_budget=0,
    search_alpha=0.05, profiling off.
    """

    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 1e-4
    workers_per_node: int = 0   # -ll:tpu — chips per host; 0 = all visible
    cpus_per_node: int = 1      # -ll:cpu
    num_nodes: int = 1          # --nodes
    profiling: bool = False
    # -p/--print-freq: epochs between metric prints in fit().  The
    # reference parses printFreq (model.cc:1223-1226) into config.h:85 but
    # never reads it; here it actually gates the epoch line.
    print_frequency: int = 1
    # strategy search knobs (reference model.cc:1253-1260)
    search_budget: int = 0      # --budget: MCMC iterations
    search_alpha: float = 0.05  # --alpha: annealing temperature
    search_chains: int = 1      # --chains: independent MCMC chains
    search_overlap_backward_update: bool = False
    # --search-precision: grow the SOAP space with the per-op precision
    # axis (ISSUE 14) — MCMC proposals may flip one op between bf16 and
    # f32 (loss/norm-statistics ops stay pinned f32 by the FF140
    # legality pass) alongside partitioning mutations, and the cost
    # model charges dtype-dependent compute rate + HBM traffic per op.
    # OFF by default: the proposal distribution (and therefore every
    # acceptance decision) is bit-identical to a build without the axis.
    search_precision: bool = False
    # --search-mode: "mcmc" (the pure anneal, the historical default —
    # fixed-seed bit-identical across releases) or "hybrid" (ISSUE 20:
    # exact DP over decomposable subgraphs + cost-guided MCMC on the
    # residual cross-region variables, docs/strategy_search.md "Exact
    # DP on decomposable subgraphs")
    search_mode: str = "mcmc"
    # --best-known: on-disk BestStrategyStore JSON for warm-started
    # transfer — seeds the search from the best prior strategy recorded
    # for the same graph digest/device count/estimator, and records the
    # winner back when it improves on the stored entry
    best_known_file: str = ""
    # --reshard-budget: MCMC iterations for the IN-THE-LOOP re-search an
    # elastic reshard point runs (FFModel.reshard / reshard-on-resume,
    # docs/elastic.md "Resharding").  None = reuse search_budget; the
    # delta-sim SimSession makes even the full budget cheap, but a
    # reshard pause is latency the training loop feels, so this can be
    # dialed down independently.  0 disables re-search at reshard points
    # (strategies rescale onto the new mesh's data axis instead).
    reshard_search_budget: Optional[int] = None
    import_strategy_file: str = ""
    export_strategy_file: str = ""
    # TPU-native additions
    dataset_path: str = ""
    seed: int = 0
    compute_dtype: str = "bfloat16"  # MXU-native compute dtype
    param_dtype: str = "float32"
    mesh_shape: Optional[Dict[str, int]] = None  # explicit mesh override
    simulator_mode: str = "analytic"  # "analytic" | "measure"
    # Profile-calibrated cost model (search/calibration.py,
    # docs/strategy_search.md "Calibration").  calibration_file points at
    # a CalibrationTable JSON harvested by `flexflow-tpu calibrate`;
    # cost_estimator picks the per-op time model the simulator searches
    # with: "analytic" (the raw roofline), "table" (roofline rescaled by
    # measured/analytic ratios), "ridge" (learned regression over op
    # features, arXiv 2008.01040), or "auto" (= "table" when a file is
    # set, "analytic" otherwise).  With no file and the default "auto",
    # nothing is loaded and every simulator output is bit-identical to
    # an uncalibrated build.
    calibration_file: str = ""
    cost_estimator: str = "auto"  # auto | analytic | table | ridge
    remat: bool = False  # jax.checkpoint the forward pass
    # internal conv/pool layout: "nchw" (reference parity), "nhwc"
    # (channels-minor = TPU lane dim), or "auto" (currently nchw until the
    # on-chip A/B lands — flip after measurement, see BASELINE.md)
    conv_layout: str = "auto"
    # Pallas flash-attention kernel.  None = auto: flash at s >= 1024
    # (measured on v5e: flash 2.7-2.8x faster at s=1024..3072, only
    # source of attention at s >= 8192 where the dense f32 score matrix
    # exceeds HBM; XLA's fused dense attention wins below s=1024 — see
    # BASELINE.md "Flash attention").  True/False force the choice.
    flash_attention: Optional[bool] = None
    # when set, fit() wraps the epoch loop in a jax.profiler trace whose
    # dump lands here (TensorBoard-loadable) — the XLA-level complement of
    # --profiling's per-op table
    trace_dir: str = ""
    # Observability plane (flexflow_tpu/obs, docs/observability.md).
    # trace_sample_rate: fraction of submit()/fit() requests that get a
    # request-scoped span trace (0 = tracing fully off — the hot path
    # pays one lock-free boolean check per dispatch; 1.0 = every
    # request, deterministic systematic sampling, no RNG).  Export the
    # recorded spans with `flexflow-tpu trace export`.
    trace_sample_rate: float = 0.0
    # metrics_port: serve the process metrics registry's Prometheus
    # text exposition on GET /metrics at this port (stdlib HTTP, daemon
    # thread; 0 = no endpoint).  The registry backs the
    # serve_stats/gen_stats events, so the scrape and the event stream
    # cannot diverge.  metrics_host defaults to LOOPBACK — the
    # exposition names tenants and their traffic; binding a routable
    # interface ("0.0.0.0" for a cluster scraper) is an explicit
    # choice via --metrics-host.
    metrics_port: int = 0
    metrics_host: str = "127.0.0.1"
    # Gradient accumulation: split each batch into k equal microbatches
    # inside the ONE jitted train step (lax.scan), accumulate grads, and
    # apply a single optimizer update — activation memory scales with
    # the microbatch while the effective batch stays cfg.batch_size.
    # Equivalent to the full-batch step for deterministic forwards under
    # both mean- and sum-reduced losses (loss/metric sums exact with
    # equal microbatch sizes).  Caveats: dropout draws a fresh mask per
    # microbatch (a DIFFERENT, equally valid realization than one
    # full-batch mask), and batchnorm running stats take the LAST
    # microbatch's measurement once per step.  batch_size must divide
    # by k (checked at compile()).
    gradient_accumulation_steps: int = 1
    # Fused multi-step dispatch: fit() stages windows of K device-resident
    # batches and executes ONE jitted donated lax.scan over the K train
    # steps, so per-step host work (Python dispatch, eager _repin_host
    # transfers, callbacks bookkeeping) is paid once per WINDOW instead of
    # once per step — the TPU-native analogue of the reference's Legion
    # index launches over the batch partition
    # (flexflow_dataloader.cc:260-330).  K=1 keeps the current
    # one-dispatch-per-step behavior bit-exactly.  Semantics at K>1
    # (docs/performance.md "Fused multi-step dispatch"):
    #   * params/opt_state are threaded and donated across the window;
    #     per-step losses and metric sums accumulate on device and are
    #     fetched once per epoch;
    #   * faults.on_step indices round UP to the window edge (a
    #     kill_at_step:5 under K=4 fires after step 8 — the elastic
    #     recovery matrix stays honest, tests/test_faults.py);
    #   * checkpoint cadence (ModelCheckpoint / save_checkpoint in
    #     callbacks) is window-aligned: epoch boundaries always are;
    #   * composes with gradient_accumulation_steps (the accumulation
    #     scan nests INSIDE each step of the window scan).
    steps_per_dispatch: int = 1
    # Opt-in padded-tail training: fit() consumes the tail samples that do
    # not fill a whole batch (PrefetchLoader pads them to batch_size and
    # the train step masks the padding out of loss/metrics/grads) instead
    # of silently dropping them.  The masked step is mathematically the
    # mean/sum over the VALID rows only; batchnorm running stats and
    # per-microbatch dropout masks still see the padded rows (documented
    # caveat, like gradient accumulation's batchnorm note above).
    pad_tail_batches: bool = False
    # Serving engine knobs (flexflow_tpu/serving, docs/serving.md).
    # serve_max_batch: largest packed micro-batch the inference engine
    # dispatches (0 = batch_size); also the largest shape bucket, so the
    # AOT warmup compiles every bucket up to it at startup.
    serve_max_batch: int = 0
    # serve_max_wait_ms: micro-batcher coalescing deadline — a pending
    # request is dispatched no later than this many ms after it was
    # submitted, even if the batch is not full (latency floor under
    # light load; under heavy load batches fill before the deadline).
    serve_max_wait_ms: float = 2.0
    # serve_max_queue_rows: bounded-queue admission control (docs/
    # serving.md "Overload, SLOs & degradation").  0 = unbounded (the
    # fair-weather default: nothing is ever rejected/shed, the
    # un-overloaded path is bit-identical to an engine without
    # admission control).  > 0 bounds the micro-batcher's pending rows;
    # serve_admission picks what happens to a submit() that would
    # overflow it: "block" (wait for room — backpressure), "reject"
    # (fail fast with OverloadError, nothing queued) or "shed_oldest"
    # (evict the oldest queued request of the lowest priority class not
    # above the incoming one, failing it with SheddedError).
    serve_max_queue_rows: int = 0
    serve_admission: str = "block"
    # serve_starvation_ms: anti-starvation aging bound for priority
    # classes — a queued request older than this jumps the priority
    # order, so sustained high-priority load delays low-priority work
    # but can never starve it.  0 disables aging (strict priority).
    serve_starvation_ms: float = 250.0
    # serve_model_name: the tenant identity serving engines stamp on
    # their serve_stats/gen_stats/serve_health events (docs/serving.md
    # "Model fleets").  In a multi-model process (FleetEngine) every
    # tenant gets its registry name automatically; set this for a
    # single-engine deployment whose event stream will be merged with
    # others' ("" = untagged single-engine default).
    serve_model_name: str = ""
    # serve_quantize: weight quantization for the serving bucket
    # executables (docs/serving.md "Int8 weight quantization").  "" =
    # off (the default — serving params, executables and results are
    # bit-identical to a build without quantization); "int8" =
    # per-output-channel symmetric int8 weight-only quantization of the
    # eligible matmul kernels (FFModel.quantize_weights), dequant fused
    # into the matmul, with a max-abs-error quality bound checked at
    # engine warmup.  Halves-to-quarters the weights' HBM residency and
    # bandwidth; the fleet gate's resident_bytes accounting follows
    # byte-for-byte.
    serve_quantize: str = ""
    # serve_buckets: explicit comma-separated batch buckets ("2,4,16,64");
    # empty = powers of two 2,4,...,serve_max_batch (the default omits
    # bucket 1 to keep results packing-invariant — single-row programs
    # hit matrix-vector kernels whose bits differ ~1 ulp; opt in via an
    # explicit list, see serving/batcher.derive_buckets).  Each bucket
    # is lowered + AOT-compiled once at engine startup
    # (FFModel.forward_compiled) and reused for every packed batch.
    serve_buckets: str = ""
    # Token-generation serving (flexflow_tpu/serving/generation,
    # docs/serving.md "Token generation").  serve_gen_slots: width of
    # the continuous-batching decode batch — the number of concurrent
    # streams sharing one KV cache and one decode dispatch per step
    # (>= 2: a 1-slot decode lowers matrix-vector kernels and breaks
    # the decode==forward parity pin, like serve_buckets' floor).
    serve_gen_slots: int = 8
    # serve_gen_max_seq: per-slot KV-cache length (prompt + generated
    # tokens); 0 = the model's input sequence length.  Drives the
    # preallocated HBM the FF108/FF121 gates account with
    # `lint --serve-slots` (analysis/kv_memory.py).
    serve_gen_max_seq: int = 0
    # serve_gen_max_new_tokens: default generation budget per request
    # when submit() does not specify one.
    serve_gen_max_new_tokens: int = 32
    # Paged KV cache (docs/serving.md "Paged KV & prefix caching").
    # serve_kv_page: tokens per KV page — the sharing/allocation
    # granularity of the generation engine's page pool (and the prefix
    # cache's match granularity: only full pages are shareable).
    serve_kv_page: int = 16
    # serve_kv_pages: total pool pages; 0 = auto, the dense worst case
    # slots x ceil(max_seq / page) so the accounting equals the old
    # dense preallocation (analysis/kv_memory.py) — shrink it once the
    # bench's high-water evidence says so.  Undersized pools shed
    # streams (KVCacheExhausted) after LRU-evicting cached prefixes.
    serve_kv_pages: int = 0
    # serve_prefix_cache: "on" (default) caches full pages of prompt
    # prefixes in a ref-counted trie so shared system prompts skip
    # their prefill; "off" disables it — tokens are bit-identical
    # either way (the ISSUE 15 correctness anchor), only TTFT and
    # pages-in-use change.
    serve_prefix_cache: str = "on"
    # serve_prefill_chunk: prefill long prompts in chunks of this many
    # tokens, at most one chunk per decode-step boundary, capping the
    # decode stall a joining prompt inflicts on in-flight streams
    # (Sarathi-style).  0 = whole-prompt chunks (the monolithic
    # baseline serve-bench --generate compares against).
    serve_prefill_chunk: int = 0
    # Speculative decoding (docs/serving.md "Speculative decoding &
    # sampling").  serve_spec_gamma: draft tokens proposed per round
    # when a draft model is attached — 0 = off, else >= 2 (a 1-row
    # verify window lowers matrix-vector kernels whose bits drift,
    # same floor as serve_gen_slots/serve_buckets).  Only consulted
    # when the engine is given a draft model.
    serve_spec_gamma: int = 0
    # serve_spec_gamma_max: ceiling for the adaptive controller's γ
    # candidates (and a sanity bound for the fixed policy).
    serve_spec_gamma_max: int = 4
    # serve_spec_policy: "fixed" runs serve_spec_gamma every round;
    # "adaptive" prices candidate γs from the live accept-rate EWMA
    # against their calibrated round cost and retunes periodically.
    serve_spec_policy: str = "fixed"
    # Sparse embedding-table updates (reference parity: the embedding
    # backward scatter-accumulates only the touched rows,
    # embedding.cu:192-228 — it never streams the full table).  A dense
    # jax autodiff update instead materializes a table-shaped gradient
    # and the optimizer rewrites every row: ~4 full-table HBM passes per
    # step, which dominates DLRM-class models.  "auto" = use the sparse
    # path (autodiff w.r.t. the gathered rows + scatter-add update, an
    # EXACT rewrite of plain-SGD) whenever the optimizer is SGD with
    # momentum=0/weight_decay=0, the table is device-placed, unshared,
    # and the id tensor is a graph input; True forces eligible tables,
    # False disables.
    sparse_embedding_updates: Optional[bool] = None  # None = auto

    # resolved at FFModel construction
    strategies: Dict[str, ParallelConfig] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        # fail at construction with the FIELD name — an unknown dtype
        # string used to surface as an opaque jnp.dtype error deep
        # inside the first trace (ISSUE 14 satellite)
        _validate_dtype_field("compute_dtype", self.compute_dtype,
                              VALID_COMPUTE_DTYPES)
        _validate_dtype_field("param_dtype", self.param_dtype,
                              VALID_PARAM_DTYPES)
        if self.serve_quantize not in ("", "int8"):
            raise ValueError(
                f"FFConfig.serve_quantize must be '' or 'int8', got "
                f"{self.serve_quantize!r}")
        if self.serve_prefix_cache not in ("on", "off"):
            raise ValueError(
                f"FFConfig.serve_prefix_cache must be 'on' or 'off', "
                f"got {self.serve_prefix_cache!r}")
        if self.serve_kv_page < 1:
            raise ValueError(
                f"FFConfig.serve_kv_page must be >= 1, got "
                f"{self.serve_kv_page}")
        if self.serve_kv_pages < 0 or self.serve_prefill_chunk < 0:
            raise ValueError(
                f"FFConfig.serve_kv_pages/serve_prefill_chunk must be "
                f">= 0 (0 = auto/monolithic), got "
                f"{self.serve_kv_pages}/{self.serve_prefill_chunk}")
        if self.serve_spec_gamma != 0 and self.serve_spec_gamma < 2:
            raise ValueError(
                f"FFConfig.serve_spec_gamma must be 0 (off) or >= 2, "
                f"got {self.serve_spec_gamma}")
        if self.serve_spec_gamma_max < 2:
            raise ValueError(
                f"FFConfig.serve_spec_gamma_max must be >= 2, got "
                f"{self.serve_spec_gamma_max}")
        if self.serve_spec_policy not in ("fixed", "adaptive"):
            raise ValueError(
                f"FFConfig.serve_spec_policy must be 'fixed' or "
                f"'adaptive', got {self.serve_spec_policy!r}")

    @property
    def num_devices(self) -> int:
        return max(1, self.workers_per_node) * self.num_nodes

    def precision_policy(self) -> str:
        """Short human/bench tag of the run's precision policy, stamped
        next to device_kind/calibration_digest in bench rows: the global
        compute dtype ("bf16"/"f32"/...), "+mixed(B/F)" when per-op
        strategy overrides are present (B ops bf16, F ops f32), and
        "+int8w" under serving weight quantization."""
        short = dtype_short(self.compute_dtype)
        nb = sum(1 for pc in self.strategies.values()
                 if pc is not None and pc.precision == "bf16")
        nf = sum(1 for pc in self.strategies.values()
                 if pc is not None and pc.precision == "f32")
        if nb or nf:
            short += f"+mixed({nb}bf16/{nf}f32)"
        if self.serve_quantize:
            short += f"+{self.serve_quantize}w"
        return short

    @staticmethod
    def parse_args(argv: Optional[List[str]] = None) -> "FFConfig":
        """CLI parser with the reference's flag set (model.cc:1221-1289):
        ``-e/--epochs -b/--batch-size --lr/--learning-rate --wd/--weight-decay
        -p/--print-freq -d/--dataset --budget --alpha -s/--export -import/
        --import -ll:tpu -ll:gpu -ll:cpu --nodes --profiling --overlap``."""
        import sys

        if argv is None:
            argv = sys.argv[1:]
        cfg = FFConfig()
        i = 0
        while i < len(argv):
            a = argv[i]

            def val() -> str:
                nonlocal i
                i += 1
                return argv[i]

            if a in ("-e", "--epochs"):
                cfg.epochs = int(val())
            elif a in ("-b", "--batch-size"):
                cfg.batch_size = int(val())
            elif a in ("--lr", "--learning-rate"):
                cfg.learning_rate = float(val())
            elif a in ("--wd", "--weight-decay"):
                cfg.weight_decay = float(val())
            elif a in ("-p", "--print-freq"):
                cfg.print_frequency = max(1, int(val()))
            elif a in ("-d", "--dataset"):
                cfg.dataset_path = val()
            elif a == "--budget":
                cfg.search_budget = int(val())
            elif a == "--alpha":
                cfg.search_alpha = float(val())
            elif a == "--chains":
                cfg.search_chains = max(1, int(val()))
            elif a == "--search-precision":
                cfg.search_precision = True
            elif a == "--search-mode":
                mode = val().lower()
                if mode not in ("mcmc", "hybrid"):
                    raise ValueError(
                        f"--search-mode {mode!r}: want 'mcmc' or 'hybrid'")
                cfg.search_mode = mode
            elif a == "--best-known":
                cfg.best_known_file = val()
            elif a == "--reshard-budget":
                cfg.reshard_search_budget = int(val())
            elif a == "--calibration":
                cfg.calibration_file = val()
            elif a == "--cost-estimator":
                cfg.cost_estimator = val().lower()
            elif a == "--overlap":
                cfg.search_overlap_backward_update = True
            elif a in ("-s", "--export"):
                cfg.export_strategy_file = val()
            elif a in ("-import", "--import"):
                cfg.import_strategy_file = val()
            elif a in ("-ll:tpu", "-ll:gpu"):
                cfg.workers_per_node = int(val())
            elif a == "-ll:cpu":
                cfg.cpus_per_node = int(val())
            elif a == "--nodes":
                cfg.num_nodes = int(val())
            elif a == "--profiling":
                cfg.profiling = True
            elif a == "--seed":
                cfg.seed = int(val())
            elif a == "--remat":
                cfg.remat = True
            elif a == "--conv-layout":
                cfg.conv_layout = val().lower()
            elif a == "--accum-steps":
                cfg.gradient_accumulation_steps = int(val())
            elif a == "--steps-per-dispatch":
                cfg.steps_per_dispatch = int(val())
            elif a == "--pad-tail":
                cfg.pad_tail_batches = True
            elif a == "--serve-max-batch":
                cfg.serve_max_batch = int(val())
            elif a == "--serve-max-wait-ms":
                cfg.serve_max_wait_ms = float(val())
            elif a == "--serve-buckets":
                cfg.serve_buckets = val()
            elif a == "--serve-quantize":
                cfg.serve_quantize = val().lower()
                if cfg.serve_quantize not in ("", "int8"):
                    raise ValueError(
                        f"--serve-quantize must be '' or 'int8', got "
                        f"{cfg.serve_quantize!r}")
            elif a == "--compute-dtype":
                cfg.compute_dtype = val().lower()
                _validate_dtype_field("compute_dtype", cfg.compute_dtype,
                                      VALID_COMPUTE_DTYPES)
            elif a == "--param-dtype":
                cfg.param_dtype = val().lower()
                _validate_dtype_field("param_dtype", cfg.param_dtype,
                                      VALID_PARAM_DTYPES)
            elif a == "--serve-model-name":
                cfg.serve_model_name = val()
            elif a == "--serve-max-queue-rows":
                cfg.serve_max_queue_rows = int(val())
            elif a == "--serve-admission":
                cfg.serve_admission = val().lower()
            elif a == "--serve-starvation-ms":
                cfg.serve_starvation_ms = float(val())
            elif a == "--serve-gen-slots":
                cfg.serve_gen_slots = int(val())
            elif a == "--serve-gen-max-seq":
                cfg.serve_gen_max_seq = int(val())
            elif a == "--serve-gen-max-new":
                cfg.serve_gen_max_new_tokens = int(val())
            elif a == "--serve-kv-page":
                cfg.serve_kv_page = int(val())
            elif a == "--serve-kv-pages":
                cfg.serve_kv_pages = int(val())
            elif a == "--serve-prefix-cache":
                cfg.serve_prefix_cache = val().lower()
                if cfg.serve_prefix_cache not in ("on", "off"):
                    raise ValueError(
                        f"--serve-prefix-cache must be 'on' or 'off', "
                        f"got {cfg.serve_prefix_cache!r}")
            elif a == "--serve-prefill-chunk":
                cfg.serve_prefill_chunk = int(val())
            elif a == "--serve-spec-gamma":
                cfg.serve_spec_gamma = int(val())
            elif a == "--serve-spec-gamma-max":
                cfg.serve_spec_gamma_max = int(val())
            elif a == "--serve-spec-policy":
                cfg.serve_spec_policy = val().lower()
                if cfg.serve_spec_policy not in ("fixed", "adaptive"):
                    raise ValueError(
                        f"--serve-spec-policy must be 'fixed' or "
                        f"'adaptive', got {cfg.serve_spec_policy!r}")
            elif a == "--trace-sample-rate":
                cfg.trace_sample_rate = float(val())
            elif a == "--metrics-port":
                cfg.metrics_port = int(val())
            elif a == "--metrics-host":
                cfg.metrics_host = val()
            # unknown flags pass through (reference forwards Legion flags)
            i += 1
        return cfg
