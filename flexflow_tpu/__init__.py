"""flexflow_tpu — a TPU-native distributed DNN training framework.

A ground-up re-design of FlexFlow (MLSys'19; reference at /root/reference)
for TPUs: the operator set, FFModel graph API, SOAP parallelization-strategy
search, and training runtime are rebuilt on jax/XLA — Legion tasks become one
fused SPMD XLA program, Legion partitions become ``jax.sharding`` named-mesh
annotations, Legion DMA/GASNet become ICI/DCN collectives emitted by GSPMD,
and the CUDA/cuDNN kernels become XLA HLO (+ Pallas for the hot paths).
"""

from . import losses, metrics
from .config import (CompMode, DeviceType, FFConfig, MemoryType,
                     ParallelConfig)
from .initializers import (ConstantInitializer, GlorotUniform,
                           NormInitializer, UniformInitializer,
                           ZeroInitializer)
from .metrics import PerfMetrics
from .model import FFModel
from .op import Op, OpType
from .optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .parallel.mesh import MachineMesh
from .tensor import Parameter, Tensor

__version__ = "0.1.0"

LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = losses.SPARSE_CATEGORICAL_CROSSENTROPY
LOSS_CATEGORICAL_CROSSENTROPY = losses.CATEGORICAL_CROSSENTROPY
LOSS_MEAN_SQUARED_ERROR = losses.MEAN_SQUARED_ERROR
METRICS_ACCURACY = metrics.ACCURACY
METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = metrics.SPARSE_CATEGORICAL_CROSSENTROPY
METRICS_CATEGORICAL_CROSSENTROPY = metrics.CATEGORICAL_CROSSENTROPY
METRICS_MEAN_SQUARED_ERROR = metrics.MEAN_SQUARED_ERROR
