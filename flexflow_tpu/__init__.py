"""flexflow_tpu — a TPU-native distributed DNN training framework.

A ground-up re-design of FlexFlow (MLSys'19; reference at /root/reference)
for TPUs: the operator set, FFModel graph API, SOAP parallelization-strategy
search, and training runtime are rebuilt on jax/XLA — Legion tasks become one
fused SPMD XLA program, Legion partitions become ``jax.sharding`` named-mesh
annotations, Legion DMA/GASNet become ICI/DCN collectives emitted by GSPMD,
and the CUDA/cuDNN kernels become XLA HLO (+ Pallas for the hot paths).
"""

import os as _os

if _os.environ.get("FLEXFLOW_PLATFORM"):
    # Force the jax backend through jax.config: embedded hosts (C API) and
    # subprocess tests cannot rely on JAX_PLATFORMS alone because a
    # pre-registered accelerator PJRT plugin may override the env var.
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["FLEXFLOW_PLATFORM"])

from . import losses, metrics, obs
from .analysis import (Diagnostic, DiagnosticReport, Severity,
                       VerificationError, verify)
from .config import (CompMode, DeviceType, FFConfig, MemoryType,
                     ParallelConfig)
from .initializers import (ConstantInitializer, GlorotUniform,
                           NormInitializer, UniformInitializer,
                           ZeroInitializer)
from .metrics import PerfMetrics
from .model import FFModel
from .op import Op, OpType
from .optimizers import AdamOptimizer, Optimizer, SGDOptimizer
from .parallel.mesh import MachineMesh
from .serving import (DeadlineExceeded, GenerationCancelled,
                      GenerationEngine, GenerationStream, KVCacheExhausted,
                      OverloadError, ServingEngine, ServingError,
                      SheddedError)
from .tensor import Parameter, Tensor

__version__ = "0.2.0"

_default_config: "FFConfig | None" = None


def set_default_config(cfg: FFConfig) -> None:
    """Install the process-wide default FFConfig (used by the
    ``flexflow-tpu`` script runner, cli.py)."""
    global _default_config
    _default_config = cfg


def get_default_config() -> FFConfig:
    """A fresh copy per call — models must not share mutable strategy state
    (compile() writes searched strategies into its config)."""
    import copy
    if _default_config is None:
        return FFConfig()
    return copy.deepcopy(_default_config)

LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = losses.SPARSE_CATEGORICAL_CROSSENTROPY
LOSS_CATEGORICAL_CROSSENTROPY = losses.CATEGORICAL_CROSSENTROPY
LOSS_MEAN_SQUARED_ERROR = losses.MEAN_SQUARED_ERROR
METRICS_ACCURACY = metrics.ACCURACY
METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = metrics.SPARSE_CATEGORICAL_CROSSENTROPY
METRICS_CATEGORICAL_CROSSENTROPY = metrics.CATEGORICAL_CROSSENTROPY
METRICS_MEAN_SQUARED_ERROR = metrics.MEAN_SQUARED_ERROR
