"""``precision-bench`` — the ISSUE 14 evidence artifact
(``artifacts/precision_bench_r15.json``): what the precision axis and
int8 weight quantization are worth on the producing host.

Four sections, one JSON payload:

* **search** — MCMC over the transformer zoo graph on an f32-charged
  simulator, with vs without the precision axis: the mixed-precision
  strategy's simulated step time must beat the all-f32 baseline (the
  acceptance criterion), and the bf16 op count shows WHERE the axis
  spent its headroom.  Deterministic (seeded, analytic objective) —
  this section is host-independent.
* **train** — measured ``fit()`` steps/s under the bf16 vs f32 global
  policy (``FFConfig.compute_dtype``), through train-bench's machinery.
  Recorded honestly either way: on CPU hosts bf16 is emulated and
  usually SLOWER — the row exists so on-TPU runs have a comparable
  artifact, not to claim a CPU win.
* **serve** — measured serving rows/s, int8 weight-quantized buckets vs
  the full-precision baseline (same model, same engine knobs), plus the
  quantization quality report: ``max_abs_err`` vs the symmetric-
  rounding ``error_bound``, and ``bound_ok`` (the engine refuses to
  serve when it fails — the artifact records it passing).
* provenance — device_kind, backend, precision-policy tags per row
  (the same stamping convention as train/serve/search-bench).

Run: ``python -m flexflow_tpu.cli precision-bench [--budget 300]
[--steps 48] [--epochs 2] [--requests 192] [--seed 0] [--out f.json]``
— JSON on stdout either way.  CPU-runnable end to end.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Dict


def bench_search(budget: int = 300, seed: int = 0,
                 num_devices: int = 8) -> Dict:
    """Precision-axis search win on the zoo transformer, simulated on
    an f32-charged objective (dtype_bytes=4)."""
    from .config import FFConfig
    from .models import build_transformer
    from .search.mcmc import search
    from .search.simulator import Simulator

    cfg = FFConfig(batch_size=32, compute_dtype="float32")
    model, _, _ = build_transformer(cfg, num_layers=2, d_model=128,
                                    num_heads=4, d_ff=256, seq_len=64,
                                    vocab_size=1000)

    def run(precision_axis: bool):
        sim = Simulator(num_devices=num_devices, dtype_bytes=4,
                        compute_dtype="float32")
        return search(model.layers, num_devices, budget=budget,
                      seed=seed, sim=sim, precision_axis=precision_axis)

    best, mesh, mixed_t = run(True)
    _, _, base_t = run(False)
    n_bf16 = sum(1 for pc in best.values() if pc.precision == "bf16")
    n_f32 = sum(1 for pc in best.values() if pc.precision == "f32")
    return {
        "graph": "transformer",
        "num_devices": num_devices,
        "budget": budget,
        "baseline_all_f32_ms": round(base_t * 1e3, 6),
        "mixed_precision_ms": round(mixed_t * 1e3, 6),
        "speedup": round(base_t / mixed_t, 4) if mixed_t else None,
        "mixed_beats_baseline": mixed_t < base_t,
        "bf16_ops": n_bf16,
        "f32_pinned_ops": n_f32,
        "best_mesh": {a: s for a, s in mesh.items() if s > 1},
        "precision_policy": "f32+mixed(search)",
    }


def bench_train(steps: int = 48, epochs: int = 2, seed: int = 0) -> Dict:
    """Measured fit() steps/s, bf16 vs f32 global policy (train-bench's
    bench_k at K=1)."""
    from .train_bench import bench_k

    rows = {}
    for dtype in ("float32", "bfloat16"):
        r = bench_k(1, steps=steps, epochs=epochs, seed=seed,
                    compute_dtype=dtype)
        rows[dtype] = {"steps_per_sec": r["steps_per_sec"],
                       "ms_per_step": r["ms_per_step"],
                       "precision_policy": r["precision_policy"]}
    f32 = rows["float32"]["steps_per_sec"]
    bf16 = rows["bfloat16"]["steps_per_sec"]
    return {**rows, "bf16_over_f32": round(bf16 / max(1e-9, f32), 3)}


def bench_serve(requests: int = 192, max_batch: int = 32,
                hidden: int = 256, seed: int = 0) -> Dict:
    """Measured serving rows/s, int8-quantized vs baseline buckets —
    same graph/weights/knobs, best of two legs each (host hiccups only
    inflate wall-clock)."""
    import flexflow_tpu as ff
    from .fflogger import silenced
    from .parallel.mesh import MachineMesh
    from .serving.bench import NFEAT, make_requests
    from .serving.engine import ServingEngine

    def build(quantize: str):
        cfg = ff.FFConfig(batch_size=max_batch, compute_dtype="float32",
                          seed=seed, serve_max_batch=max_batch,
                          serve_quantize=quantize)
        m = ff.FFModel(cfg, mesh=MachineMesh({"n": 1}))
        x = m.create_tensor((max_batch, NFEAT), name="x")
        t = m.dense(x, hidden, activation="relu")
        t = m.dense(t, hidden, activation="relu")
        t = m.dense(t, 10)
        m.compile(ff.SGDOptimizer(lr=0.05))
        m.init_layers(seed=seed)
        return m

    reqs = make_requests(requests, 1, 8, seed)
    rows_total = sum(r.shape[0] for r in reqs)

    def maxrate(model) -> float:
        best = 0.0
        for _ in range(2):
            with silenced("serve"), ServingEngine(model) as eng:
                t0 = time.perf_counter()
                futs = [eng.submit(r) for r in reqs]
                for f in futs:
                    f.result(timeout=120)
                dt = time.perf_counter() - t0
            best = max(best, rows_total / dt)
        return round(best, 2)

    base_model = build("")
    base_rps = maxrate(base_model)
    q_model = build("int8")
    with silenced("serve"):
        q_rps = maxrate(q_model)
    qrep = q_model._quant_report
    return {
        "requests": requests,
        "rows": rows_total,
        "baseline_rows_per_s": base_rps,
        "int8_rows_per_s": q_rps,
        "int8_over_baseline": round(q_rps / max(1e-9, base_rps), 3),
        "baseline_policy": base_model.config.precision_policy(),
        "int8_policy": q_model.config.precision_policy(),
        "quality": {
            "max_abs_err": qrep["max_abs_err"],
            "error_bound": qrep["error_bound"],
            "bound_ok": qrep["bound_ok"],
            "weights_quantized": len(qrep["weights"]),
            "bytes_before": qrep["bytes_before"],
            "bytes_after": qrep["bytes_after"],
        },
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="flexflow-tpu precision-bench",
        description="precision axis + int8 serving evidence artifact "
                    "(docs/performance.md 'Precision policy')")
    ap.add_argument("--budget", type=int, default=300,
                    help="MCMC iterations per search leg")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=48,
                    help="train steps per epoch")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--requests", type=int, default=192)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    import jax

    from .fflogger import get_logger
    from .search.calibration import device_kind as _device_kind
    log = get_logger("ff")
    prev_level = log.level
    log.level = 100  # this bench's stdout IS the payload
    try:
        payload = {
            "bench": "precision-bench",
            "backend": jax.default_backend(),
            "device_kind": _device_kind(),
            "seed": args.seed,
            "search": bench_search(args.budget, args.seed, args.devices),
            "train": bench_train(args.steps, args.epochs, args.seed),
            "serve": bench_serve(args.requests, seed=args.seed),
        }
    finally:
        log.level = prev_level
    text = json.dumps(payload, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
