/* flexflow_tpu_c.h — flat C API for the flexflow_tpu framework.
 *
 * Mirrors the reference's C surface (python/flexflow_c.h:49-125: opaque
 * handles for FFConfig/FFModel/Tensor plus per-op adders and training
 * verbs), so a non-Python host — or a cffi binding — can drive the full
 * graph-build / compile / train loop.  The implementation
 * (flexflow_tpu_c.cpp) embeds CPython and dispatches to the Python core:
 * on TPU the runtime under every call is the same fused XLA program, so the
 * C layer is a thin veneer by design rather than a 2k-LoC re-implementation.
 *
 * Build:  g++ -O2 -shared -fPIC flexflow_tpu_c.cpp \
 *             $(python3-config --includes) $(python3-config --ldflags --embed) \
 *             -o libflexflow_tpu_c.so
 */

#ifndef FLEXFLOW_TPU_C_H
#define FLEXFLOW_TPU_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct flexflow_config_st* flexflow_config_t;
typedef struct flexflow_model_st* flexflow_model_t;
typedef struct flexflow_tensor_st* flexflow_tensor_t;
typedef struct flexflow_optimizer_st* flexflow_optimizer_handle_t;

typedef enum { FF_DT_FLOAT = 0, FF_DT_INT32 = 1, FF_DT_INT64 = 2,
               FF_DT_DOUBLE = 3 } flexflow_datatype_t;
typedef enum { FF_AC_NONE = 0, FF_AC_RELU = 1, FF_AC_SIGMOID = 2,
               FF_AC_TANH = 3, FF_AC_GELU = 4 } flexflow_activation_t;
typedef enum { FF_OPT_SGD = 0, FF_OPT_ADAM = 1 } flexflow_optimizer_t;
typedef enum { FF_LOSS_SPARSE_CCE = 0, FF_LOSS_CCE = 1,
               FF_LOSS_MSE = 2 } flexflow_loss_t;

/* ---- runtime ---- */
/* Initialize the embedded runtime; safe to call more than once.
 * Returns 0 on success. */
int flexflow_init(void);
void flexflow_finalize(void);
/* Last error message ("" when none). */
const char* flexflow_last_error(void);

/* ---- config (reference flexflow_c.h: flexflow_config_*) ---- */
flexflow_config_t flexflow_config_create(int argc, char** argv);
void flexflow_config_destroy(flexflow_config_t);
int flexflow_config_get_batch_size(flexflow_config_t);
int flexflow_config_get_epochs(flexflow_config_t);
int flexflow_config_get_workers_per_node(flexflow_config_t);
/* NetConfig (reference flexflow_c.h:520-528, :1055): dataset path parsed
 * from the -d/--dataset flag.  Returns a pointer owned by the config —
 * valid until flexflow_config_destroy. */
const char* flexflow_config_get_dataset_path(flexflow_config_t);

/* ---- model + tensors ---- */
flexflow_model_t flexflow_model_create(flexflow_config_t);
void flexflow_model_destroy(flexflow_model_t);
flexflow_tensor_t flexflow_model_create_tensor(
    flexflow_model_t, int ndims, const int64_t* dims,
    flexflow_datatype_t dtype, const char* name);
void flexflow_tensor_destroy(flexflow_tensor_t);
int flexflow_tensor_get_ndims(flexflow_tensor_t);
int64_t flexflow_tensor_get_dim(flexflow_tensor_t, int idx);

/* ---- op adders (reference flexflow_c.h per-op surface from :133) ---- */
flexflow_tensor_t flexflow_model_conv2d(
    flexflow_model_t, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w,
    int padding_h, int padding_w, flexflow_activation_t activation,
    int use_bias, const char* name);
flexflow_tensor_t flexflow_model_pool2d(
    flexflow_model_t, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w,
    int is_max_pool, const char* name);
flexflow_tensor_t flexflow_model_dense(
    flexflow_model_t, flexflow_tensor_t input, int out_dim,
    flexflow_activation_t activation, int use_bias, const char* name);
/* aggr: "sum"/"avg" (bag mode) or "none" (sequence mode: (n,s) ids ->
 * (n,s,d)); NULL means "sum". */
flexflow_tensor_t flexflow_model_embedding(
    flexflow_model_t, flexflow_tensor_t input, int num_entries, int out_dim,
    const char* aggr, const char* name);
flexflow_tensor_t flexflow_model_flat(flexflow_model_t, flexflow_tensor_t,
                                      const char* name);
flexflow_tensor_t flexflow_model_softmax(flexflow_model_t, flexflow_tensor_t,
                                         const char* name);
flexflow_tensor_t flexflow_model_concat(flexflow_model_t, int n,
                                        flexflow_tensor_t* inputs, int axis,
                                        const char* name);
flexflow_tensor_t flexflow_model_add(flexflow_model_t, flexflow_tensor_t,
                                     flexflow_tensor_t, const char* name);
flexflow_tensor_t flexflow_model_dropout(flexflow_model_t, flexflow_tensor_t,
                                         float rate, const char* name);
flexflow_tensor_t flexflow_model_batch_norm(flexflow_model_t,
                                            flexflow_tensor_t, int relu,
                                            const char* name);
flexflow_tensor_t flexflow_model_mse_loss(flexflow_model_t, flexflow_tensor_t,
                                          const char* reduction,
                                          const char* name);
/* Element-wise families (reference per-op adders exp/relu/sigmoid/...;
 * op: "relu","gelu","sigmoid","tanh","elu","exp","identity"). */
flexflow_tensor_t flexflow_model_unary(flexflow_model_t, const char* op,
                                       flexflow_tensor_t, const char* name);
/* op: "add","sub","mul","div". */
flexflow_tensor_t flexflow_model_binary(flexflow_model_t, const char* op,
                                        flexflow_tensor_t, flexflow_tensor_t,
                                        const char* name);
flexflow_tensor_t flexflow_model_layer_norm(flexflow_model_t,
                                            flexflow_tensor_t,
                                            const char* name);
flexflow_tensor_t flexflow_model_rms_norm(flexflow_model_t, flexflow_tensor_t,
                                          const char* name);
/* Equal split into n_outputs parts along axis; fills outputs[0..n).
 * Returns 0 on success. */
int flexflow_model_split(flexflow_model_t, flexflow_tensor_t, int n_outputs,
                         int axis, flexflow_tensor_t* outputs,
                         const char* name);
flexflow_tensor_t flexflow_model_reshape(flexflow_model_t, flexflow_tensor_t,
                                         int ndims, const int64_t* dims,
                                         const char* name);
flexflow_tensor_t flexflow_model_transpose(flexflow_model_t,
                                           flexflow_tensor_t, int ndims,
                                           const int* perm, const char* name);
/* Self-attention when key/value are NULL (transformer workload). */
flexflow_tensor_t flexflow_model_multihead_attention(
    flexflow_model_t, flexflow_tensor_t query,
    flexflow_tensor_t key /* or NULL */, flexflow_tensor_t value /* or NULL */,
    int embed_dim, int num_heads, float dropout, int use_bias, int causal,
    const char* name);
flexflow_tensor_t flexflow_model_position_embedding(flexflow_model_t,
                                                    flexflow_tensor_t,
                                                    const char* name);
/* LSTM (NMT workload): returns the (n,s,H) sequence; when non-NULL,
 * h_out / c_out receive the final hidden/cell state tensors.  Pass
 * h_init/c_init (both or neither) to seed the state (encoder->decoder). */
flexflow_tensor_t flexflow_model_lstm(flexflow_model_t, flexflow_tensor_t,
                                      int hidden_size,
                                      flexflow_tensor_t h_init /* or NULL */,
                                      flexflow_tensor_t c_init /* or NULL */,
                                      flexflow_tensor_t* h_out,
                                      flexflow_tensor_t* c_out,
                                      const char* name);
/* Mixture-of-Experts FFN over the 'e' mesh axis. */
flexflow_tensor_t flexflow_model_moe(flexflow_model_t, flexflow_tensor_t,
                                     int num_experts, int d_ff, int k,
                                     float capacity_factor, const char* name);

/* ---- optimizer handles (reference flexflow_c.h sgd/adam create) ---- */
flexflow_optimizer_handle_t flexflow_sgd_optimizer_create(
    double lr, double momentum, int nesterov, double weight_decay);
flexflow_optimizer_handle_t flexflow_adam_optimizer_create(
    double alpha, double beta1, double beta2, double weight_decay,
    double epsilon);
void flexflow_optimizer_destroy(flexflow_optimizer_handle_t);

/* ---- compile + training verbs (reference flexflow_c.h:86-125) ---- */
int flexflow_model_compile(flexflow_model_t, flexflow_optimizer_t opt,
                           double lr, flexflow_loss_t loss,
                           flexflow_tensor_t final_tensor /* or NULL */);
/* Compile with a configured optimizer handle (full hyperparameters). */
int flexflow_model_compile_opt(flexflow_model_t,
                               flexflow_optimizer_handle_t opt,
                               flexflow_loss_t loss,
                               flexflow_tensor_t final_tensor /* or NULL */);
int flexflow_model_init_layers(flexflow_model_t, int seed);
/* One fused training step on host buffers (row-major, batch-major).
 * inputs[i] points at the i-th graph input; label is the label buffer.
 * Returns the loss, or NaN on error. */
double flexflow_model_train_batch(flexflow_model_t, int n_inputs,
                                  const void** inputs, const void* label);
/* Legacy verb API: set_batch then forward/zero_gradients/backward/update. */
int flexflow_model_set_batch(flexflow_model_t, int n_inputs,
                             const void** inputs, const void* label);
int flexflow_model_forward(flexflow_model_t);
int flexflow_model_zero_gradients(flexflow_model_t);
double flexflow_model_backward(flexflow_model_t);
int flexflow_model_update(flexflow_model_t);

/* ---- weights I/O (reference Parameter::get/set_weights) ---- */
/* Copies the named parameter into buf (float32); returns element count,
 * or -1 on error. Pass buf=NULL to query the size. */
int64_t flexflow_model_get_weights(flexflow_model_t, const char* name,
                                   float* buf, int64_t capacity);
int flexflow_model_set_weights(flexflow_model_t, const char* name,
                               const float* buf, int64_t count);

/* ---- strategy files (reference -import/-export, strategy.cc:87-163) ---- */
/* Stage a strategy .pb to be applied by the next compile call. */
int flexflow_model_import_strategies(flexflow_model_t, const char* path);
/* Dump the compiled per-op strategies to a strategy .pb. */
int flexflow_model_export_strategies(flexflow_model_t, const char* path);

/* ---- checkpoint (params + optimizer state + step; .npz) ---- */
int flexflow_model_save_checkpoint(flexflow_model_t, const char* path);
int flexflow_model_load_checkpoint(flexflow_model_t, const char* path);

#ifdef __cplusplus
}
#endif

#endif /* FLEXFLOW_TPU_C_H */
