// flexflow_tpu_c.cpp — C API implementation (see flexflow_tpu_c.h).
//
// Embeds CPython and dispatches every call into the flexflow_tpu package;
// handles are owned PyObject references.  Host buffers are wrapped as
// numpy arrays via memoryviews (no numpy C API dependency) — the copy to
// device memory happens inside train_batch's _shard_batch, mirroring the
// reference dataloader's host->FB copy (flexflow_dataloader.cc:260-330).

#include "flexflow_tpu_c.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_err;

void set_err_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  if (value) {
    PyObject* s = PyObject_Str(value);
    g_err = s ? PyUnicode_AsUTF8(s) : "unknown python error";
    Py_XDECREF(s);
  } else {
    g_err = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* g_ff = nullptr;  // flexflow_tpu module
PyObject* g_np = nullptr;  // numpy module

struct Handle {
  PyObject* obj;
};

PyObject* obj(void* h) { return reinterpret_cast<Handle*>(h)->obj; }

void* wrap(PyObject* o) {
  if (!o) return nullptr;
  Handle* h = new Handle{o};
  return h;
}

void unwrap_free(void* h) {
  if (!h) return;
  Handle* hh = reinterpret_cast<Handle*>(h);
  Py_XDECREF(hh->obj);
  delete hh;
}

const char* act_name(flexflow_activation_t a) {
  switch (a) {
    case FF_AC_RELU: return "relu";
    case FF_AC_SIGMOID: return "sigmoid";
    case FF_AC_TANH: return "tanh";
    case FF_AC_GELU: return "gelu";
    default: return nullptr;
  }
}

const char* loss_name(flexflow_loss_t l) {
  switch (l) {
    case FF_LOSS_CCE: return "categorical_crossentropy";
    case FF_LOSS_MSE: return "mean_squared_error";
    default: return "sparse_categorical_crossentropy";
  }
}

// per-dtype element size (np.dtype(name).itemsize, cached).  On failure
// the pending CPython exception is consumed into g_err — leaving it set
// would poison the next unrelated API call.
Py_ssize_t dtype_itemsize(const char* dtype) {
  static std::vector<std::pair<std::string, Py_ssize_t>> cache;
  for (auto& kv : cache)
    if (kv.first == dtype) return kv.second;
  PyObject* d = PyObject_CallMethod(g_np, "dtype", "s", dtype);
  if (!d) {
    set_err_from_python();
    return -1;
  }
  PyObject* sz = PyObject_GetAttrString(d, "itemsize");
  Py_DECREF(d);
  if (!sz) {
    set_err_from_python();
    return -1;
  }
  Py_ssize_t v = PyLong_AsSsize_t(sz);
  Py_DECREF(sz);
  cache.emplace_back(dtype, v);
  return v;
}

// numpy array viewing a host buffer: np.frombuffer(memoryview, dtype)
// .reshape(shape).  Returns a new reference or nullptr.
PyObject* buffer_to_ndarray(const void* data, PyObject* shape_tuple,
                            const char* dtype) {
  Py_ssize_t n = 1;
  for (Py_ssize_t i = 0; i < PyTuple_Size(shape_tuple); i++)
    n *= PyLong_AsLongLong(PyTuple_GetItem(shape_tuple, i));
  Py_ssize_t isz = dtype_itemsize(dtype);
  if (isz <= 0) {
    g_err = std::string("unknown dtype ") + dtype;
    return nullptr;
  }
  Py_ssize_t nbytes = n * isz;
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)), nbytes, PyBUF_READ);
  if (!mv) return nullptr;
  PyObject* flat = PyObject_CallMethod(g_np, "frombuffer", "Os", mv, dtype);
  Py_DECREF(mv);
  if (!flat) return nullptr;
  PyObject* arr = PyObject_CallMethod(flat, "reshape", "O", shape_tuple);
  Py_DECREF(flat);
  return arr;
}

// shapes+dtypes of the model's graph inputs followed by the label tensor
PyObject* model_feed_specs(PyObject* model) {
  // returns list of (shape tuple, dtype str) — inputs then label
  PyObject* specs = PyList_New(0);
  PyObject* inputs = PyObject_GetAttrString(model, "input_tensors");
  if (!inputs) return nullptr;
  Py_ssize_t n = PyList_Size(inputs);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* t = PyList_GetItem(inputs, i);  // borrowed
    PyObject* shape = PyObject_GetAttrString(t, "shape");
    PyObject* dtype = PyObject_GetAttrString(t, "dtype");
    PyObject* pair = PyTuple_Pack(2, shape, dtype);
    PyList_Append(specs, pair);
    Py_DECREF(pair);
    Py_DECREF(shape);
    Py_DECREF(dtype);
  }
  Py_DECREF(inputs);
  PyObject* label = PyObject_GetAttrString(model, "label_tensor");
  if (label && label != Py_None) {
    PyObject* shape = PyObject_GetAttrString(label, "shape");
    PyObject* dtype = PyObject_GetAttrString(label, "dtype");
    PyObject* pair = PyTuple_Pack(2, shape, dtype);
    PyList_Append(specs, pair);
    Py_DECREF(pair);
    Py_DECREF(shape);
    Py_DECREF(dtype);
  }
  Py_XDECREF(label);
  return specs;
}

// build the python arg tuple (x0, x1, ..., label) from raw buffers
PyObject* marshal_batch(PyObject* model, int n_inputs, const void** inputs,
                        const void* label) {
  PyObject* specs = model_feed_specs(model);
  if (!specs) return nullptr;
  if (PyList_Size(specs) != n_inputs + 1) {
    g_err = "input count mismatch: model expects " +
            std::to_string(PyList_Size(specs) - 1) + " inputs";
    Py_DECREF(specs);
    return nullptr;
  }
  PyObject* args = PyTuple_New(n_inputs + 1);
  for (int i = 0; i <= n_inputs; i++) {
    PyObject* pair = PyList_GetItem(specs, i);  // borrowed
    PyObject* shape = PyTuple_GetItem(pair, 0);
    const char* dtype = PyUnicode_AsUTF8(PyTuple_GetItem(pair, 1));
    const void* buf = (i < n_inputs) ? inputs[i] : label;
    PyObject* arr = buffer_to_ndarray(buf, shape, dtype);
    if (!arr) {
      Py_DECREF(specs);
      Py_DECREF(args);
      return nullptr;
    }
    PyTuple_SetItem(args, i, arr);  // steals
  }
  Py_DECREF(specs);
  return args;
}

}  // namespace

extern "C" {

const char* flexflow_last_error(void) { return g_err.c_str(); }

int flexflow_init(void) {
  if (g_ff) return 0;
  if (!Py_IsInitialized()) Py_InitializeEx(0);
  g_ff = PyImport_ImportModule("flexflow_tpu");
  if (!g_ff) {
    set_err_from_python();
    return -1;
  }
  g_np = PyImport_ImportModule("numpy");
  if (!g_np) {
    set_err_from_python();
    return -1;
  }
  return 0;
}

void flexflow_finalize(void) {
  Py_XDECREF(g_ff);
  Py_XDECREF(g_np);
  g_ff = g_np = nullptr;
}

/* ---- config ---- */

flexflow_config_t flexflow_config_create(int argc, char** argv) {
  if (flexflow_init() != 0) return nullptr;
  PyObject* lst = PyList_New(0);
  for (int i = 0; i < argc; i++) {
    PyObject* s = PyUnicode_FromString(argv[i]);
    PyList_Append(lst, s);
    Py_DECREF(s);
  }
  PyObject* cls = PyObject_GetAttrString(g_ff, "FFConfig");
  PyObject* cfg = PyObject_CallMethod(cls, "parse_args", "O", lst);
  Py_DECREF(cls);
  Py_DECREF(lst);
  if (!cfg) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_config_t)wrap(cfg);
}

void flexflow_config_destroy(flexflow_config_t c) { unwrap_free(c); }

static int get_int_attr(void* h, const char* name) {
  PyObject* v = PyObject_GetAttrString(obj(h), name);
  if (!v) {
    set_err_from_python();
    return -1;
  }
  long r = PyLong_AsLong(v);
  Py_DECREF(v);
  return (int)r;
}

int flexflow_config_get_batch_size(flexflow_config_t c) {
  return get_int_attr(c, "batch_size");
}
int flexflow_config_get_epochs(flexflow_config_t c) {
  return get_int_attr(c, "epochs");
}
int flexflow_config_get_workers_per_node(flexflow_config_t c) {
  return get_int_attr(c, "workers_per_node");
}

const char* flexflow_config_get_dataset_path(flexflow_config_t c) {
  static std::string path;  // lifetime: until next call (C-string handoff)
  PyObject* v = PyObject_GetAttrString(obj(c), "dataset_path");
  if (!v) {
    set_err_from_python();
    return "";
  }
  const char* s = PyUnicode_AsUTF8(v);
  path = s ? s : "";
  Py_DECREF(v);
  return path.c_str();
}

/* ---- model + tensors ---- */

flexflow_model_t flexflow_model_create(flexflow_config_t c) {
  if (flexflow_init() != 0) return nullptr;
  PyObject* cls = PyObject_GetAttrString(g_ff, "FFModel");
  PyObject* m = PyObject_CallFunctionObjArgs(cls, obj(c), nullptr);
  Py_DECREF(cls);
  if (!m) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_model_t)wrap(m);
}

void flexflow_model_destroy(flexflow_model_t m) { unwrap_free(m); }
void flexflow_tensor_destroy(flexflow_tensor_t t) { unwrap_free(t); }

flexflow_tensor_t flexflow_model_create_tensor(
    flexflow_model_t m, int ndims, const int64_t* dims,
    flexflow_datatype_t dtype, const char* name) {
  PyObject* shape = PyTuple_New(ndims);
  for (int i = 0; i < ndims; i++)
    PyTuple_SetItem(shape, i, PyLong_FromLongLong(dims[i]));
  const char* dt = "float32";
  if (dtype == FF_DT_INT32) dt = "int32";
  else if (dtype == FF_DT_INT64) dt = "int64";
  else if (dtype == FF_DT_DOUBLE) dt = "float64";
  PyObject* t = PyObject_CallMethod(
      obj(m), "create_tensor", "Oss", shape, dt, name ? name : "input");
  Py_DECREF(shape);
  if (!t) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_tensor_t)wrap(t);
}

int flexflow_tensor_get_ndims(flexflow_tensor_t t) {
  PyObject* shape = PyObject_GetAttrString(obj(t), "shape");
  int n = (int)PyTuple_Size(shape);
  Py_DECREF(shape);
  return n;
}

int64_t flexflow_tensor_get_dim(flexflow_tensor_t t, int idx) {
  PyObject* shape = PyObject_GetAttrString(obj(t), "shape");
  int64_t v = PyLong_AsLongLong(PyTuple_GetItem(shape, idx));
  Py_DECREF(shape);
  return v;
}

/* ---- op adders ---- */

static flexflow_tensor_t call_op(PyObject* result) {
  if (!result) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_tensor_t)wrap(result);
}

// method call with positional args (format) + keyword dict built from
// NULL-terminated (key, PyObject* new-ref) pairs
static PyObject* call_kw(PyObject* o, const char* meth, PyObject* args,
                         PyObject* kwargs) {
  PyObject* f = PyObject_GetAttrString(o, meth);
  if (!f) return nullptr;
  PyObject* r = PyObject_Call(f, args, kwargs);
  Py_DECREF(f);
  Py_DECREF(args);
  Py_XDECREF(kwargs);
  return r;
}

static void kw_set_str(PyObject* kw, const char* k, const char* v) {
  if (!v) return;
  PyObject* s = PyUnicode_FromString(v);
  PyDict_SetItemString(kw, k, s);
  Py_DECREF(s);
}

static void kw_set_bool(PyObject* kw, const char* k, int v) {
  PyDict_SetItemString(kw, k, v ? Py_True : Py_False);
}

static void kw_set_double(PyObject* kw, const char* k, double v) {
  PyObject* o = PyFloat_FromDouble(v);
  PyDict_SetItemString(kw, k, o);
  Py_DECREF(o);
}

static void kw_set_long(PyObject* kw, const char* k, long v) {
  PyObject* o = PyLong_FromLong(v);
  PyDict_SetItemString(kw, k, o);
  Py_DECREF(o);
}

flexflow_tensor_t flexflow_model_conv2d(
    flexflow_model_t m, flexflow_tensor_t input, int out_channels,
    int kernel_h, int kernel_w, int stride_h, int stride_w,
    int padding_h, int padding_w, flexflow_activation_t activation,
    int use_bias, const char* name) {
  PyObject* args = Py_BuildValue("(Oiiiiiii)", obj(input), out_channels,
                                 kernel_h, kernel_w, stride_h, stride_w,
                                 padding_h, padding_w);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "activation", act_name(activation));
  kw_set_bool(kw, "use_bias", use_bias);
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "conv2d", args, kw));
}

flexflow_tensor_t flexflow_model_pool2d(
    flexflow_model_t m, flexflow_tensor_t input, int kernel_h, int kernel_w,
    int stride_h, int stride_w, int padding_h, int padding_w,
    int is_max_pool, const char* name) {
  PyObject* args = Py_BuildValue("(Oiiiiii)", obj(input), kernel_h, kernel_w,
                                 stride_h, stride_w, padding_h, padding_w);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "pool_type", is_max_pool ? "max" : "avg");
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "pool2d", args, kw));
}

flexflow_tensor_t flexflow_model_dense(
    flexflow_model_t m, flexflow_tensor_t input, int out_dim,
    flexflow_activation_t activation, int use_bias, const char* name) {
  PyObject* args = Py_BuildValue("(Oi)", obj(input), out_dim);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "activation", act_name(activation));
  kw_set_bool(kw, "use_bias", use_bias);
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "dense", args, kw));
}

flexflow_tensor_t flexflow_model_embedding(
    flexflow_model_t m, flexflow_tensor_t input, int num_entries,
    int out_dim, const char* aggr, const char* name) {
  PyObject* args = Py_BuildValue("(Oii)", obj(input), num_entries, out_dim);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "aggr", aggr ? aggr : "sum");
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "embedding", args, kw));
}

flexflow_tensor_t flexflow_model_flat(flexflow_model_t m,
                                      flexflow_tensor_t input,
                                      const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "flat", args, kw));
}

flexflow_tensor_t flexflow_model_softmax(flexflow_model_t m,
                                         flexflow_tensor_t input,
                                         const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "softmax", args, kw));
}

flexflow_tensor_t flexflow_model_concat(flexflow_model_t m, int n,
                                        flexflow_tensor_t* inputs, int axis,
                                        const char* name) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; i++) {
    Py_INCREF(obj(inputs[i]));
    PyList_SetItem(lst, i, obj(inputs[i]));
  }
  PyObject* args = Py_BuildValue("(Oi)", lst, axis);
  Py_DECREF(lst);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "concat", args, kw));
}

flexflow_tensor_t flexflow_model_add(flexflow_model_t m, flexflow_tensor_t a,
                                     flexflow_tensor_t b, const char* name) {
  PyObject* args = Py_BuildValue("(OO)", obj(a), obj(b));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "add", args, kw));
}

flexflow_tensor_t flexflow_model_dropout(flexflow_model_t m,
                                         flexflow_tensor_t input, float rate,
                                         const char* name) {
  PyObject* args = Py_BuildValue("(Od)", obj(input), (double)rate);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "dropout", args, kw));
}

flexflow_tensor_t flexflow_model_batch_norm(flexflow_model_t m,
                                            flexflow_tensor_t input, int relu,
                                            const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_bool(kw, "relu", relu);
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "batch_norm", args, kw));
}

flexflow_tensor_t flexflow_model_mse_loss(flexflow_model_t m,
                                          flexflow_tensor_t logits,
                                          const char* reduction,
                                          const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(logits));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "reduction", reduction ? reduction : "average");
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "mse_loss", args, kw));
}

flexflow_tensor_t flexflow_model_unary(flexflow_model_t m, const char* op,
                                       flexflow_tensor_t input,
                                       const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  // FFModel exposes each unary as its own method (relu/gelu/exp/...)
  return call_op(call_kw(obj(m), op, args, kw));
}

flexflow_tensor_t flexflow_model_binary(flexflow_model_t m, const char* op,
                                        flexflow_tensor_t a,
                                        flexflow_tensor_t b,
                                        const char* name) {
  const char* meth = op;
  if (strcmp(op, "sub") == 0) meth = "subtract";
  else if (strcmp(op, "mul") == 0) meth = "multiply";
  else if (strcmp(op, "div") == 0) meth = "divide";
  PyObject* args = Py_BuildValue("(OO)", obj(a), obj(b));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), meth, args, kw));
}

flexflow_tensor_t flexflow_model_layer_norm(flexflow_model_t m,
                                            flexflow_tensor_t input,
                                            const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "layer_norm", args, kw));
}

flexflow_tensor_t flexflow_model_rms_norm(flexflow_model_t m,
                                          flexflow_tensor_t input,
                                          const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "rms_norm", args, kw));
}

int flexflow_model_split(flexflow_model_t m, flexflow_tensor_t input,
                         int n_outputs, int axis, flexflow_tensor_t* outputs,
                         const char* name) {
  PyObject* args = Py_BuildValue("(Oii)", obj(input), n_outputs, axis);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  PyObject* lst = call_kw(obj(m), "split", args, kw);
  if (!lst) {
    set_err_from_python();
    return -1;
  }
  for (int i = 0; i < n_outputs; i++) {
    PyObject* t = PySequence_GetItem(lst, i);  // new ref
    if (!t) {
      set_err_from_python();
      for (int j = 0; j < i; j++) {  // release partial results on error
        unwrap_free(outputs[j]);
        outputs[j] = nullptr;
      }
      Py_DECREF(lst);
      return -1;
    }
    outputs[i] = (flexflow_tensor_t)wrap(t);
  }
  Py_DECREF(lst);
  return 0;
}

flexflow_tensor_t flexflow_model_reshape(flexflow_model_t m,
                                         flexflow_tensor_t input, int ndims,
                                         const int64_t* dims,
                                         const char* name) {
  PyObject* shape = PyTuple_New(ndims);
  for (int i = 0; i < ndims; i++)
    PyTuple_SetItem(shape, i, PyLong_FromLongLong(dims[i]));
  PyObject* args = Py_BuildValue("(OO)", obj(input), shape);
  Py_DECREF(shape);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "reshape", args, kw));
}

flexflow_tensor_t flexflow_model_transpose(flexflow_model_t m,
                                           flexflow_tensor_t input, int ndims,
                                           const int* perm,
                                           const char* name) {
  PyObject* p = PyTuple_New(ndims);
  for (int i = 0; i < ndims; i++)
    PyTuple_SetItem(p, i, PyLong_FromLong(perm[i]));
  PyObject* args = Py_BuildValue("(OO)", obj(input), p);
  Py_DECREF(p);
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "transpose", args, kw));
}

flexflow_tensor_t flexflow_model_multihead_attention(
    flexflow_model_t m, flexflow_tensor_t query, flexflow_tensor_t key,
    flexflow_tensor_t value, int embed_dim, int num_heads, float dropout,
    int use_bias, int causal, const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(query));
  PyObject* kw = PyDict_New();
  if (key) PyDict_SetItemString(kw, "key", obj(key));
  if (value) PyDict_SetItemString(kw, "value", obj(value));
  kw_set_long(kw, "embed_dim", embed_dim);
  kw_set_long(kw, "num_heads", num_heads);
  kw_set_double(kw, "dropout", dropout);
  kw_set_bool(kw, "bias", use_bias);
  kw_set_bool(kw, "causal", causal);
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "multihead_attention", args, kw));
}

flexflow_tensor_t flexflow_model_position_embedding(flexflow_model_t m,
                                                    flexflow_tensor_t input,
                                                    const char* name) {
  PyObject* args = Py_BuildValue("(O)", obj(input));
  PyObject* kw = PyDict_New();
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "position_embedding", args, kw));
}

flexflow_tensor_t flexflow_model_lstm(flexflow_model_t m,
                                      flexflow_tensor_t input,
                                      int hidden_size,
                                      flexflow_tensor_t h_init,
                                      flexflow_tensor_t c_init,
                                      flexflow_tensor_t* h_out,
                                      flexflow_tensor_t* c_out,
                                      const char* name) {
  PyObject* args = Py_BuildValue("(Oi)", obj(input), hidden_size);
  PyObject* kw = PyDict_New();
  if (h_init && c_init) {
    PyObject* st = PyTuple_Pack(2, obj(h_init), obj(c_init));
    PyDict_SetItemString(kw, "initial_state", st);
    Py_DECREF(st);
  }
  kw_set_str(kw, "name", name);
  PyObject* tup = call_kw(obj(m), "lstm", args, kw);
  if (!tup) {
    set_err_from_python();
    return nullptr;
  }
  PyObject* seq = PySequence_GetItem(tup, 0);
  if (h_out) *h_out = (flexflow_tensor_t)wrap(PySequence_GetItem(tup, 1));
  if (c_out) *c_out = (flexflow_tensor_t)wrap(PySequence_GetItem(tup, 2));
  Py_DECREF(tup);
  return (flexflow_tensor_t)wrap(seq);
}

flexflow_tensor_t flexflow_model_moe(flexflow_model_t m,
                                     flexflow_tensor_t input, int num_experts,
                                     int d_ff, int k, float capacity_factor,
                                     const char* name) {
  PyObject* args = Py_BuildValue("(Oii)", obj(input), num_experts, d_ff);
  PyObject* kw = PyDict_New();
  kw_set_long(kw, "k", k);
  kw_set_double(kw, "capacity_factor", capacity_factor);
  kw_set_str(kw, "name", name);
  return call_op(call_kw(obj(m), "moe", args, kw));
}

/* ---- optimizer handles ---- */

flexflow_optimizer_handle_t flexflow_sgd_optimizer_create(
    double lr, double momentum, int nesterov, double weight_decay) {
  if (flexflow_init() != 0) return nullptr;
  PyObject* cls = PyObject_GetAttrString(g_ff, "SGDOptimizer");
  PyObject* kw = PyDict_New();
  kw_set_double(kw, "lr", lr);
  kw_set_double(kw, "momentum", momentum);
  kw_set_bool(kw, "nesterov", nesterov);
  kw_set_double(kw, "weight_decay", weight_decay);
  PyObject* empty = PyTuple_New(0);
  PyObject* o = PyObject_Call(cls, empty, kw);
  Py_DECREF(cls);
  Py_DECREF(empty);
  Py_DECREF(kw);
  if (!o) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_optimizer_handle_t)wrap(o);
}

flexflow_optimizer_handle_t flexflow_adam_optimizer_create(
    double alpha, double beta1, double beta2, double weight_decay,
    double epsilon) {
  if (flexflow_init() != 0) return nullptr;
  PyObject* cls = PyObject_GetAttrString(g_ff, "AdamOptimizer");
  PyObject* kw = PyDict_New();
  kw_set_double(kw, "alpha", alpha);
  kw_set_double(kw, "beta1", beta1);
  kw_set_double(kw, "beta2", beta2);
  kw_set_double(kw, "weight_decay", weight_decay);
  kw_set_double(kw, "epsilon", epsilon);
  PyObject* empty = PyTuple_New(0);
  PyObject* o = PyObject_Call(cls, empty, kw);
  Py_DECREF(cls);
  Py_DECREF(empty);
  Py_DECREF(kw);
  if (!o) {
    set_err_from_python();
    return nullptr;
  }
  return (flexflow_optimizer_handle_t)wrap(o);
}

void flexflow_optimizer_destroy(flexflow_optimizer_handle_t o) {
  unwrap_free(o);
}

/* ---- compile + verbs ---- */

int flexflow_model_compile(flexflow_model_t m, flexflow_optimizer_t opt,
                           double lr, flexflow_loss_t loss,
                           flexflow_tensor_t final_tensor) {
  PyObject* cls = PyObject_GetAttrString(
      g_ff, opt == FF_OPT_ADAM ? "AdamOptimizer" : "SGDOptimizer");
  PyObject* okw = PyDict_New();
  PyObject* lrv = PyFloat_FromDouble(lr);
  PyDict_SetItemString(okw, opt == FF_OPT_ADAM ? "alpha" : "lr", lrv);
  Py_DECREF(lrv);
  PyObject* empty = PyTuple_New(0);
  PyObject* opt_obj = PyObject_Call(cls, empty, okw);
  Py_DECREF(cls);
  Py_DECREF(empty);
  Py_DECREF(okw);
  if (!opt_obj) {
    set_err_from_python();
    return -1;
  }
  PyObject* args = Py_BuildValue("(Os)", opt_obj, loss_name(loss));
  PyObject* kw = PyDict_New();
  PyDict_SetItemString(kw, "final_tensor",
                       final_tensor ? obj(final_tensor) : Py_None);
  PyObject* r = call_kw(obj(m), "compile", args, kw);
  Py_DECREF(opt_obj);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int flexflow_model_compile_opt(flexflow_model_t m,
                               flexflow_optimizer_handle_t opt,
                               flexflow_loss_t loss,
                               flexflow_tensor_t final_tensor) {
  PyObject* args = Py_BuildValue("(Os)", obj(opt), loss_name(loss));
  PyObject* kw = PyDict_New();
  PyDict_SetItemString(kw, "final_tensor",
                       final_tensor ? obj(final_tensor) : Py_None);
  PyObject* r = call_kw(obj(m), "compile", args, kw);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int flexflow_model_init_layers(flexflow_model_t m, int seed) {
  PyObject* r = PyObject_CallMethod(obj(m), "init_layers", "i", seed);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

double flexflow_model_train_batch(flexflow_model_t m, int n_inputs,
                                  const void** inputs, const void* label) {
  PyObject* args = marshal_batch(obj(m), n_inputs, inputs, label);
  if (!args) {
    if (PyErr_Occurred()) set_err_from_python();
    return (double)NAN;
  }
  PyObject* fn = PyObject_GetAttrString(obj(m), "train_batch");
  PyObject* loss = fn ? PyObject_CallObject(fn, args) : nullptr;
  Py_XDECREF(fn);
  Py_DECREF(args);
  if (!loss) {
    set_err_from_python();
    return (double)NAN;
  }
  double v = PyFloat_AsDouble(loss);
  Py_DECREF(loss);
  return v;
}

int flexflow_model_set_batch(flexflow_model_t m, int n_inputs,
                             const void** inputs, const void* label) {
  PyObject* args = marshal_batch(obj(m), n_inputs, inputs, label);
  if (!args) {
    if (PyErr_Occurred()) set_err_from_python();
    return -1;
  }
  PyObject* fn = PyObject_GetAttrString(obj(m), "set_batch");
  PyObject* r = fn ? PyObject_CallObject(fn, args) : nullptr;
  Py_XDECREF(fn);
  Py_DECREF(args);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

static int call_verb(flexflow_model_t m, const char* verb) {
  PyObject* r = PyObject_CallMethod(obj(m), verb, nullptr);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int flexflow_model_forward(flexflow_model_t m) {
  return call_verb(m, "forward");
}
int flexflow_model_zero_gradients(flexflow_model_t m) {
  return call_verb(m, "zero_gradients");
}
int flexflow_model_update(flexflow_model_t m) { return call_verb(m, "update"); }

double flexflow_model_backward(flexflow_model_t m) {
  PyObject* r = PyObject_CallMethod(obj(m), "backward", nullptr);
  if (!r) {
    set_err_from_python();
    return (double)NAN;
  }
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  return v;
}

/* ---- weights ---- */

int64_t flexflow_model_get_weights(flexflow_model_t m, const char* name,
                                   float* buf, int64_t capacity) {
  PyObject* w = PyObject_CallMethod(obj(m), "get_weights", "s", name);
  if (!w) {
    set_err_from_python();
    return -1;
  }
  PyObject* w32 = PyObject_CallMethod(w, "astype", "s", "float32");
  Py_DECREF(w);
  if (!w32) {
    set_err_from_python();
    return -1;
  }
  PyObject* flat = PyObject_CallMethod(w32, "ravel", nullptr);
  Py_DECREF(w32);
  PyObject* size = PyObject_GetAttrString(flat, "size");
  int64_t n = PyLong_AsLongLong(size);
  Py_DECREF(size);
  if (buf) {
    if (capacity < n) {
      g_err = "buffer too small";
      Py_DECREF(flat);
      return -1;
    }
    PyObject* bytes = PyObject_CallMethod(flat, "tobytes", nullptr);
    memcpy(buf, PyBytes_AsString(bytes), (size_t)n * 4);
    Py_DECREF(bytes);
  }
  Py_DECREF(flat);
  return n;
}

int flexflow_model_set_weights(flexflow_model_t m, const char* name,
                               const float* buf, int64_t count) {
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(reinterpret_cast<const char*>(buf)), count * 4,
      PyBUF_READ);
  PyObject* arr = PyObject_CallMethod(g_np, "frombuffer", "Os", mv,
                                      "float32");
  Py_DECREF(mv);
  if (!arr) {
    set_err_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(obj(m), "set_weights", "sO", name, arr);
  Py_DECREF(arr);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

/* ---- strategy files ---- */

int flexflow_model_import_strategies(flexflow_model_t m, const char* path) {
  PyObject* cfg = PyObject_GetAttrString(obj(m), "config");
  if (!cfg) {
    set_err_from_python();
    return -1;
  }
  PyObject* s = PyUnicode_FromString(path);
  int rc = PyObject_SetAttrString(cfg, "import_strategy_file", s);
  Py_DECREF(s);
  Py_DECREF(cfg);
  if (rc != 0) {
    set_err_from_python();
    return -1;
  }
  return 0;
}

int flexflow_model_export_strategies(flexflow_model_t m, const char* path) {
  PyObject* mod = PyImport_ImportModule("flexflow_tpu.strategy.proto");
  if (!mod) {
    set_err_from_python();
    return -1;
  }
  // {op.name: op.parallel_config for op in m.layers if op.parallel_config}
  PyObject* strategies = PyDict_New();
  PyObject* layers = PyObject_GetAttrString(obj(m), "layers");
  for (Py_ssize_t i = 0; layers && i < PyList_Size(layers); i++) {
    PyObject* op = PyList_GetItem(layers, i);  // borrowed
    PyObject* pc = PyObject_GetAttrString(op, "parallel_config");
    if (pc && pc != Py_None) {
      PyObject* nm = PyObject_GetAttrString(op, "name");
      PyDict_SetItem(strategies, nm, pc);
      Py_DECREF(nm);
    }
    Py_XDECREF(pc);
  }
  Py_XDECREF(layers);
  PyObject* r = PyObject_CallMethod(mod, "save_strategy_file", "sO", path,
                                    strategies);
  Py_DECREF(strategies);
  Py_DECREF(mod);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

/* ---- checkpoint ---- */

static int ckpt_call(flexflow_model_t m, const char* meth, const char* path) {
  PyObject* r = PyObject_CallMethod(obj(m), meth, "s", path);
  if (!r) {
    set_err_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

int flexflow_model_save_checkpoint(flexflow_model_t m, const char* path) {
  return ckpt_call(m, "save_checkpoint", path);
}

int flexflow_model_load_checkpoint(flexflow_model_t m, const char* path) {
  return ckpt_call(m, "load_checkpoint", path);
}

}  // extern "C"
