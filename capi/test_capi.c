/* C API smoke test: build an MLP through the flat C surface, train a few
 * steps, verify the loss is finite and decreasing — the reference's C API
 * consumers (cffi, C hosts) drive exactly this call sequence
 * (flexflow_c.h:86-125). */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_tpu_c.h"

#define BATCH 16
#define IN_DIM 8
#define CLASSES 4

int main(void) {
  if (flexflow_init() != 0) {
    fprintf(stderr, "init failed: %s\n", flexflow_last_error());
    return 1;
  }
  char* argv[] = {(char*)"-b", (char*)"16", (char*)"-e", (char*)"1"};
  flexflow_config_t cfg = flexflow_config_create(4, argv);
  if (!cfg || flexflow_config_get_batch_size(cfg) != BATCH) {
    fprintf(stderr, "config failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_model_t model = flexflow_model_create(cfg);
  int64_t dims[] = {BATCH, IN_DIM};
  flexflow_tensor_t x =
      flexflow_model_create_tensor(model, 2, dims, FF_DT_FLOAT, "x");
  flexflow_tensor_t t =
      flexflow_model_dense(model, x, 32, FF_AC_RELU, 1, "fc1");
  flexflow_tensor_t logits =
      flexflow_model_dense(model, t, CLASSES, FF_AC_NONE, 1, "fc2");
  flexflow_tensor_t probs = flexflow_model_softmax(model, logits, "softmax");
  if (!probs) {
    fprintf(stderr, "graph failed: %s\n", flexflow_last_error());
    return 1;
  }
  if (flexflow_tensor_get_ndims(probs) != 2 ||
      flexflow_tensor_get_dim(probs, 1) != CLASSES) {
    fprintf(stderr, "bad output shape\n");
    return 1;
  }
  if (flexflow_model_compile(model, FF_OPT_SGD, 0.1, FF_LOSS_SPARSE_CCE,
                             probs) != 0 ||
      flexflow_model_init_layers(model, 0) != 0) {
    fprintf(stderr, "compile failed: %s\n", flexflow_last_error());
    return 1;
  }

  float xb[BATCH * IN_DIM];
  int32_t yb[BATCH];
  srand(0);
  for (int i = 0; i < BATCH; i++) {
    yb[i] = i % CLASSES;
    for (int j = 0; j < IN_DIM; j++)
      xb[i * IN_DIM + j] =
          0.05f * ((float)rand() / RAND_MAX - 0.5f) + (j == yb[i] ? 1.f : 0.f);
  }
  const void* inputs[] = {xb};
  double first = 0, loss = 0;
  for (int it = 0; it < 10; it++) {
    loss = flexflow_model_train_batch(model, 1, inputs, yb);
    if (isnan(loss)) {
      fprintf(stderr, "train failed: %s\n", flexflow_last_error());
      return 1;
    }
    if (it == 0) first = loss;
  }
  printf("first loss %.4f -> last loss %.4f\n", first, loss);
  if (!(loss < first)) {
    fprintf(stderr, "loss did not decrease\n");
    return 1;
  }

  /* verbs + weights round trip */
  if (flexflow_model_set_batch(model, 1, inputs, yb) != 0 ||
      flexflow_model_forward(model) != 0 ||
      flexflow_model_zero_gradients(model) != 0) {
    fprintf(stderr, "verbs failed: %s\n", flexflow_last_error());
    return 1;
  }
  double vloss = flexflow_model_backward(model);
  if (isnan(vloss) || flexflow_model_update(model) != 0) {
    fprintf(stderr, "backward/update failed: %s\n", flexflow_last_error());
    return 1;
  }
  int64_t n = flexflow_model_get_weights(model, "fc1/kernel", NULL, 0);
  if (n != 32 * IN_DIM) {
    fprintf(stderr, "get_weights size %lld: %s\n", (long long)n,
            flexflow_last_error());
    return 1;
  }
  float* w = (float*)malloc(n * sizeof(float));
  if (flexflow_model_get_weights(model, "fc1/kernel", w, n) != n) return 1;
  for (int64_t i = 0; i < n; i++) w[i] = 0.5f;
  if (flexflow_model_set_weights(model, "fc1/kernel", w, n) != 0) return 1;
  if (flexflow_model_get_weights(model, "fc1/kernel", w, n) != n) return 1;
  if (fabsf(w[7] - 0.5f) > 1e-6f) {
    fprintf(stderr, "set/get weights mismatch\n");
    return 1;
  }
  free(w);
  flexflow_tensor_destroy(x);
  flexflow_tensor_destroy(t);
  flexflow_tensor_destroy(logits);
  flexflow_tensor_destroy(probs);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  printf("C API OK\n");
  return 0;
}
