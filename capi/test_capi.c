/* C API smoke test: build an MLP through the flat C surface, train a few
 * steps, verify the loss is finite and decreasing — the reference's C API
 * consumers (cffi, C hosts) drive exactly this call sequence
 * (flexflow_c.h:86-125). */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_tpu_c.h"

#define BATCH 16
#define IN_DIM 8
#define CLASSES 4

int main(void) {
  if (flexflow_init() != 0) {
    fprintf(stderr, "init failed: %s\n", flexflow_last_error());
    return 1;
  }
  char* argv[] = {(char*)"-b", (char*)"16", (char*)"-e", (char*)"1"};
  flexflow_config_t cfg = flexflow_config_create(4, argv);
  if (!cfg || flexflow_config_get_batch_size(cfg) != BATCH) {
    fprintf(stderr, "config failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_model_t model = flexflow_model_create(cfg);
  int64_t dims[] = {BATCH, IN_DIM};
  flexflow_tensor_t x =
      flexflow_model_create_tensor(model, 2, dims, FF_DT_FLOAT, "x");
  flexflow_tensor_t t =
      flexflow_model_dense(model, x, 32, FF_AC_RELU, 1, "fc1");
  flexflow_tensor_t logits =
      flexflow_model_dense(model, t, CLASSES, FF_AC_NONE, 1, "fc2");
  flexflow_tensor_t probs = flexflow_model_softmax(model, logits, "softmax");
  if (!probs) {
    fprintf(stderr, "graph failed: %s\n", flexflow_last_error());
    return 1;
  }
  if (flexflow_tensor_get_ndims(probs) != 2 ||
      flexflow_tensor_get_dim(probs, 1) != CLASSES) {
    fprintf(stderr, "bad output shape\n");
    return 1;
  }
  if (flexflow_model_compile(model, FF_OPT_SGD, 0.1, FF_LOSS_SPARSE_CCE,
                             probs) != 0 ||
      flexflow_model_init_layers(model, 0) != 0) {
    fprintf(stderr, "compile failed: %s\n", flexflow_last_error());
    return 1;
  }

  float xb[BATCH * IN_DIM];
  int32_t yb[BATCH];
  srand(0);
  for (int i = 0; i < BATCH; i++) {
    yb[i] = i % CLASSES;
    for (int j = 0; j < IN_DIM; j++)
      xb[i * IN_DIM + j] =
          0.05f * ((float)rand() / RAND_MAX - 0.5f) + (j == yb[i] ? 1.f : 0.f);
  }
  const void* inputs[] = {xb};
  double first = 0, loss = 0;
  for (int it = 0; it < 10; it++) {
    loss = flexflow_model_train_batch(model, 1, inputs, yb);
    if (isnan(loss)) {
      fprintf(stderr, "train failed: %s\n", flexflow_last_error());
      return 1;
    }
    if (it == 0) first = loss;
  }
  printf("first loss %.4f -> last loss %.4f\n", first, loss);
  if (!(loss < first)) {
    fprintf(stderr, "loss did not decrease\n");
    return 1;
  }

  /* verbs + weights round trip */
  if (flexflow_model_set_batch(model, 1, inputs, yb) != 0 ||
      flexflow_model_forward(model) != 0 ||
      flexflow_model_zero_gradients(model) != 0) {
    fprintf(stderr, "verbs failed: %s\n", flexflow_last_error());
    return 1;
  }
  double vloss = flexflow_model_backward(model);
  if (isnan(vloss) || flexflow_model_update(model) != 0) {
    fprintf(stderr, "backward/update failed: %s\n", flexflow_last_error());
    return 1;
  }
  int64_t n = flexflow_model_get_weights(model, "fc1/kernel", NULL, 0);
  if (n != 32 * IN_DIM) {
    fprintf(stderr, "get_weights size %lld: %s\n", (long long)n,
            flexflow_last_error());
    return 1;
  }
  float* w = (float*)malloc(n * sizeof(float));
  if (flexflow_model_get_weights(model, "fc1/kernel", w, n) != n) return 1;
  for (int64_t i = 0; i < n; i++) w[i] = 0.5f;
  if (flexflow_model_set_weights(model, "fc1/kernel", w, n) != 0) return 1;
  if (flexflow_model_get_weights(model, "fc1/kernel", w, n) != n) return 1;
  if (fabsf(w[7] - 0.5f) > 1e-6f) {
    fprintf(stderr, "set/get weights mismatch\n");
    return 1;
  }
  free(w);
  flexflow_tensor_destroy(x);
  flexflow_tensor_destroy(t);
  flexflow_tensor_destroy(logits);
  flexflow_tensor_destroy(probs);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  printf("MLP OK\n");

  /* ---- transformer block end-to-end (VERDICT Missing#1: a C host must
   * be able to build the transformer workload) ---- */
  enum { TB = 8, TS = 8, TD = 16, TV = 64, TC = 4 };
  char* targv[] = {(char*)"-b", (char*)"8"};
  flexflow_config_t tcfg = flexflow_config_create(2, targv);
  flexflow_model_t tm = flexflow_model_create(tcfg);
  int64_t tok_dims[] = {TB, TS};
  flexflow_tensor_t tok =
      flexflow_model_create_tensor(tm, 2, tok_dims, FF_DT_INT32, "tokens");
  flexflow_tensor_t emb =
      flexflow_model_embedding(tm, tok, TV, TD, "none", "tok_embed");
  flexflow_tensor_t pos =
      flexflow_model_position_embedding(tm, emb, "pos_embed");
  flexflow_tensor_t attn = flexflow_model_multihead_attention(
      tm, pos, NULL, NULL, TD, 2, 0.0f, 1, 1, "attn");
  flexflow_tensor_t res1 = flexflow_model_binary(tm, "add", pos, attn, "res1");
  flexflow_tensor_t ln1 = flexflow_model_layer_norm(tm, res1, "ln1");
  flexflow_tensor_t up = flexflow_model_dense(tm, ln1, 32, FF_AC_GELU, 1,
                                              "ffn_up");
  flexflow_tensor_t dn = flexflow_model_dense(tm, up, TD, FF_AC_NONE, 1,
                                              "ffn_down");
  flexflow_tensor_t res2 = flexflow_model_binary(tm, "add", ln1, dn, "res2");
  flexflow_tensor_t ln2 = flexflow_model_layer_norm(tm, res2, "ln2");
  int64_t flat_dims[] = {TB, TS * TD};
  flexflow_tensor_t fl = flexflow_model_reshape(tm, ln2, 2, flat_dims, "fl");
  flexflow_tensor_t tlogits =
      flexflow_model_dense(tm, fl, TC, FF_AC_NONE, 1, "cls");
  if (!tlogits) {
    fprintf(stderr, "transformer graph failed: %s\n", flexflow_last_error());
    return 1;
  }
  if (flexflow_tensor_get_ndims(ln2) != 3 ||
      flexflow_tensor_get_dim(ln2, 2) != TD) {
    fprintf(stderr, "bad transformer shapes\n");
    return 1;
  }
  flexflow_optimizer_handle_t adam =
      flexflow_adam_optimizer_create(0.01, 0.9, 0.999, 0.0, 1e-8);
  if (!adam ||
      flexflow_model_compile_opt(tm, adam, FF_LOSS_SPARSE_CCE, tlogits) != 0 ||
      flexflow_model_init_layers(tm, 0) != 0) {
    fprintf(stderr, "transformer compile failed: %s\n",
            flexflow_last_error());
    return 1;
  }
  int32_t ttok[TB * TS];
  int32_t ty[TB];
  for (int i = 0; i < TB; i++) {
    ty[i] = i % TC;
    for (int s = 0; s < TS; s++)
      /* class-dependent token pattern -> learnable */
      ttok[i * TS + s] = (ty[i] * 7 + s) % TV;
  }
  const void* tin[] = {ttok};
  double tfirst = 0, tloss = 0;
  for (int it = 0; it < 12; it++) {
    tloss = flexflow_model_train_batch(tm, 1, tin, ty);
    if (isnan(tloss)) {
      fprintf(stderr, "transformer train failed: %s\n",
              flexflow_last_error());
      return 1;
    }
    if (it == 0) tfirst = tloss;
  }
  printf("transformer first loss %.4f -> last %.4f\n", tfirst, tloss);
  if (!(tloss < tfirst)) {
    fprintf(stderr, "transformer loss did not decrease\n");
    return 1;
  }

  /* checkpoint round trip: save, clobber a weight, load, verify restore */
  if (flexflow_model_save_checkpoint(tm, "/tmp/capi_ckpt") != 0) {
    fprintf(stderr, "save_checkpoint failed: %s\n", flexflow_last_error());
    return 1;
  }
  int64_t nw = flexflow_model_get_weights(tm, "cls/kernel", NULL, 0);
  float* orig = (float*)malloc(nw * sizeof(float));
  float* tmp = (float*)malloc(nw * sizeof(float));
  flexflow_model_get_weights(tm, "cls/kernel", orig, nw);
  for (int64_t i = 0; i < nw; i++) tmp[i] = -9.0f;
  flexflow_model_set_weights(tm, "cls/kernel", tmp, nw);
  if (flexflow_model_load_checkpoint(tm, "/tmp/capi_ckpt") != 0) {
    fprintf(stderr, "load_checkpoint failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_model_get_weights(tm, "cls/kernel", tmp, nw);
  for (int64_t i = 0; i < nw; i++) {
    if (fabsf(tmp[i] - orig[i]) > 1e-6f) {
      fprintf(stderr, "checkpoint did not restore weights\n");
      return 1;
    }
  }
  free(orig);
  free(tmp);

  /* strategy export produces a parseable .pb */
  if (flexflow_model_export_strategies(tm, "/tmp/capi_strategy.pb") != 0) {
    fprintf(stderr, "export_strategies failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_model_destroy(tm);
  flexflow_config_destroy(tcfg);
  printf("transformer OK\n");

  /* ---- LSTM seq2seq slice through C (NMT workload surface) ---- */
  char* largv[] = {(char*)"-b", (char*)"8"};
  flexflow_config_t lcfg = flexflow_config_create(2, largv);
  flexflow_model_t lm = flexflow_model_create(lcfg);
  int64_t ldims[] = {8, 6};
  flexflow_tensor_t ltok =
      flexflow_model_create_tensor(lm, 2, ldims, FF_DT_INT32, "src");
  flexflow_tensor_t lemb =
      flexflow_model_embedding(lm, ltok, 32, 16, "none", "src_embed");
  flexflow_tensor_t hf = NULL, cf = NULL;
  flexflow_tensor_t lseq =
      flexflow_model_lstm(lm, lemb, 16, NULL, NULL, &hf, &cf, "enc");
  flexflow_tensor_t lseq2 =
      flexflow_model_lstm(lm, lemb, 16, hf, cf, NULL, NULL, "dec");
  flexflow_tensor_t lproj =
      flexflow_model_dense(lm, lseq2, 32, FF_AC_NONE, 1, "vocab_proj");
  (void)lseq;
  if (!lproj) {
    fprintf(stderr, "lstm graph failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_optimizer_handle_t sgd =
      flexflow_sgd_optimizer_create(0.1, 0.9, 0, 0.0);
  if (flexflow_model_compile_opt(lm, sgd, FF_LOSS_SPARSE_CCE, lproj) != 0 ||
      flexflow_model_init_layers(lm, 0) != 0) {
    fprintf(stderr, "lstm compile failed: %s\n", flexflow_last_error());
    return 1;
  }
  int32_t lsrc[8 * 6], lys[8 * 6];
  for (int i = 0; i < 8 * 6; i++) {
    lsrc[i] = i % 32;
    lys[i] = (i + 1) % 32;
  }
  const void* lin[] = {lsrc};
  double lloss = flexflow_model_train_batch(lm, 1, lin, lys);
  if (isnan(lloss)) {
    fprintf(stderr, "lstm train failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_model_destroy(lm);
  flexflow_config_destroy(lcfg);
  printf("lstm OK (loss %.4f)\n", lloss);

  printf("C API OK\n");
  return 0;
}
