/* AlexNet as a pure-C app over the flat C API — the analogue of the
 * reference's flagship C++ harness (examples/cpp/AlexNet/alexnet.cc:34-131):
 * build the conv stack, compile, train on synthetic data with the timing
 * fence OUTSIDE the loop, and print the reference's ELAPSED/THROUGHPUT
 * line.  Build: make -C capi examples  Run: capi/examples/alexnet [-e N]
 */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include "../flexflow_tpu_c.h"

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

int main(int argc, char** argv) {
  if (flexflow_init() != 0) {
    fprintf(stderr, "init failed: %s\n", flexflow_last_error());
    return 1;
  }
  flexflow_config_t cfg = flexflow_config_create(argc - 1, argv + 1);
  if (!cfg) {
    fprintf(stderr, "config failed: %s\n", flexflow_last_error());
    return 1;
  }
  int batch = flexflow_config_get_batch_size(cfg);
  int epochs = flexflow_config_get_epochs(cfg);
  flexflow_model_t model = flexflow_model_create(cfg);

  /* reference alexnet.cc:41-66 stack (227x227 input variant at 64px for a
   * CPU-friendly smoke; pass -b to scale) */
  int img = 64;
  int64_t dims[] = {batch, 3, img, img};
  flexflow_tensor_t x =
      flexflow_model_create_tensor(model, 4, dims, FF_DT_FLOAT, "input");
/* a NULL tensor would segfault the next adder (handles are deref'd
 * unchecked in the C layer), so every layer is checked */
#define CK(t, what)                                                     \
  if (!(t)) {                                                           \
    fprintf(stderr, what " failed: %s\n", flexflow_last_error());       \
    return 1;                                                           \
  }
  flexflow_tensor_t t =
      flexflow_model_conv2d(model, x, 64, 11, 11, 4, 4, 2, 2, FF_AC_RELU, 1,
                            "conv1");
  CK(t, "conv1");
  t = flexflow_model_pool2d(model, t, 3, 3, 2, 2, 0, 0, 1, "pool1");
  CK(t, "pool1");
  t = flexflow_model_conv2d(model, t, 192, 5, 5, 1, 1, 2, 2, FF_AC_RELU, 1,
                            "conv2");
  CK(t, "conv2");
  t = flexflow_model_pool2d(model, t, 3, 3, 2, 2, 0, 0, 1, "pool2");
  CK(t, "pool2");
  t = flexflow_model_conv2d(model, t, 384, 3, 3, 1, 1, 1, 1, FF_AC_RELU, 1,
                            "conv3");
  CK(t, "conv3");
  t = flexflow_model_conv2d(model, t, 256, 3, 3, 1, 1, 1, 1, FF_AC_RELU, 1,
                            "conv4");
  CK(t, "conv4");
  t = flexflow_model_conv2d(model, t, 256, 3, 3, 1, 1, 1, 1, FF_AC_RELU, 1,
                            "conv5");
  CK(t, "conv5");
  t = flexflow_model_pool2d(model, t, 3, 3, 2, 2, 0, 0, 1, "pool3");
  CK(t, "pool3");
  t = flexflow_model_flat(model, t, "flat");
  CK(t, "flat");
  t = flexflow_model_dense(model, t, 4096, FF_AC_RELU, 1, "fc6");
  CK(t, "fc6");
  t = flexflow_model_dense(model, t, 4096, FF_AC_RELU, 1, "fc7");
  CK(t, "fc7");
  flexflow_tensor_t logits =
      flexflow_model_dense(model, t, 10, FF_AC_NONE, 1, "fc8");
  CK(logits, "fc8");
  flexflow_tensor_t probs = flexflow_model_softmax(model, logits, "softmax");
  CK(probs, "softmax");
  if (flexflow_model_compile(model, FF_OPT_SGD, 0.01, FF_LOSS_SPARSE_CCE,
                             probs) != 0 ||
      flexflow_model_init_layers(model, 0) != 0) {
    fprintf(stderr, "compile failed: %s\n", flexflow_last_error());
    return 1;
  }

  /* synthetic data, staged once (reference alexnet.cc:80-88 random init) */
  int n = batch * 3 * img * img;
  float* xb = (float*)malloc(sizeof(float) * n);
  int32_t* yb = (int32_t*)malloc(sizeof(int32_t) * batch);
  srand(0);
  for (int i = 0; i < n; i++) xb[i] = (float)rand() / RAND_MAX;
  for (int i = 0; i < batch; i++) yb[i] = rand() % 10;
  const void* inputs[] = {xb};

  /* warm up (compile), then the fenced timing region
   * (alexnet.cc:90-95,120-126) */
  double loss = flexflow_model_train_batch(model, 1, inputs, yb);
  if (isnan(loss)) {  /* header contract: NaN means the step failed */
    fprintf(stderr, "train failed: %s\n", flexflow_last_error());
    return 1;
  }
  int iters = 4 * epochs;
  double t0 = now_s();
  for (int it = 0; it < iters; it++) {
    loss = flexflow_model_train_batch(model, 1, inputs, yb);
    if (isnan(loss)) break; /* a failed step must abort the timing loop,
                             * not be timed into the THROUGHPUT line */
  }
  double dt = now_s() - t0;
  if (isnan(loss)) {
    fprintf(stderr, "train failed: %s\n", flexflow_last_error());
    return 1;
  }
  printf("final loss %.4f\n", loss);
  printf("ELAPSED TIME = %.4fs, THROUGHPUT = %.2f samples/s\n", dt,
         (double)batch * iters / dt);
  free(xb);
  free(yb);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  flexflow_finalize();
  return 0;
}
